"""The repo-tailored JAX-footgun rules.

Each rule is pure AST analysis over one ``LintModule``; none of them
import jax. They are deliberately conservative — a rule that cries wolf
gets suppressed wholesale and teaches nothing — so each encodes the
narrow shape of a footgun this codebase (or its reference) actually hit.
ANALYSIS.md carries the catalog with rationale and examples.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Dict, List, Optional

from .core import Finding, LintModule, dotted_name, last_segment


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    description: str
    check: Callable[[LintModule], List[Finding]]


def _finding(module: LintModule, rule_id: str, node: ast.AST, msg: str) -> Finding:
    return Finding(
        rule=rule_id,
        path=module.path,
        line=getattr(node, "lineno", 0),
        col=getattr(node, "col_offset", 0),
        message=msg,
    )


# --------------------------------------------------------------------------
# JG001 — host sync inside a traced function
# --------------------------------------------------------------------------

_NUMPY_ALIASES = {"np", "numpy", "onp"}
_SYNC_ATTRS = {"item", "block_until_ready", "tolist", "copy_to_host_async"}


def check_host_sync(module: LintModule) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call) or not module.is_traced(node):
            continue
        func = node.func
        if isinstance(func, ast.Name) and func.id == "float" and node.args:
            out.append(
                _finding(
                    module, "JG001", node,
                    "float() on a traced value — host sync / trace-time "
                    "concretization inside a jitted function",
                )
            )
        elif isinstance(func, ast.Attribute) and func.attr in _SYNC_ATTRS:
            out.append(
                _finding(
                    module, "JG001", node,
                    f".{func.attr}() inside a traced function forces a "
                    "device->host sync (or fails to trace)",
                )
            )
        elif (
            isinstance(func, ast.Attribute)
            and func.attr in ("asarray", "array")
            and isinstance(func.value, ast.Name)
            and func.value.id in _NUMPY_ALIASES
        ):
            out.append(
                _finding(
                    module, "JG001", node,
                    f"{func.value.id}.{func.attr}() inside a traced "
                    "function pulls the value to host numpy — use jnp",
                )
            )
    return out


# --------------------------------------------------------------------------
# JG002 — PRNG key hygiene
# --------------------------------------------------------------------------

_SAMPLERS = {
    "normal", "uniform", "randint", "bernoulli", "categorical",
    "permutation", "choice", "gumbel", "truncated_normal", "laplace",
    "exponential", "poisson", "gamma", "beta", "dirichlet", "cauchy",
    "rademacher", "bits", "ball", "loggamma", "maxwell", "t",
}


def _in_test_function(module: LintModule, node: ast.AST) -> bool:
    cur = module.nearest_def(node)
    while cur is not None:
        if getattr(cur, "name", "").startswith("test"):
            return True
        cur = module.nearest_def(cur)
    return False


def _jax_random_names(module: LintModule):
    """(dotted-prefix aliases of jax.random, bare names imported from
    it) — so `random.uniform(lo, hi)` from the *stdlib* is never
    mistaken for a PRNG sampler. `import jax` always contributes the
    canonical 'jax.random' prefix."""
    prefixes = {"jax.random"}
    bare = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.random" and a.asname:
                    prefixes.add(a.asname)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name == "random":
                        prefixes.add(a.asname or "random")
            elif node.module == "jax.random":
                for a in node.names:
                    bare.add(a.asname or a.name)
    return prefixes, bare


def check_prng_hygiene(module: LintModule) -> List[Finding]:
    if module.is_test_file():
        return []
    jr_prefixes, jr_bare = _jax_random_names(module)
    out: List[Finding] = []
    # (a) hardcoded seeds
    for node in ast.walk(module.tree):
        if (
            isinstance(node, ast.Call)
            and last_segment(node.func) == "PRNGKey"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, int)
            and not _in_test_function(module, node)
        ):
            out.append(
                _finding(
                    module, "JG002", node,
                    f"hardcoded PRNGKey({node.args[0].value}) in library "
                    "code — accept or derive the seed (split/fold_in) so "
                    "runs are reproducible *and* controllable",
                )
            )
    # (b) key reuse: the same name fed to >= 2 sampling calls with no
    # rebinding in between (per scope, lexical order)
    uses: Dict[tuple, List[int]] = {}
    rebinds: Dict[tuple, List[int]] = {}
    for node in ast.walk(module.tree):
        scope = module.enclosing_scope(node)
        if isinstance(node, ast.Call):
            seg = last_segment(node.func)
            dn = dotted_name(node.func) or ""
            from_jax_random = (
                any(dn == f"{p}.{seg}" for p in jr_prefixes)
                or (dn == seg and seg in jr_bare)
            )
            if (
                seg in _SAMPLERS
                and from_jax_random
                and node.args
                and isinstance(node.args[0], ast.Name)
            ):
                uses.setdefault((scope, node.args[0].id), []).append(
                    node.lineno
                )
        for tgt_name, lineno in _assigned_names(node):
            rebinds.setdefault((scope, tgt_name), []).append(lineno)
    for (scope, name), lines in uses.items():
        lines = sorted(lines)
        bind_lines = sorted(rebinds.get((scope, name), []))
        for prev, cur in zip(lines, lines[1:]):
            if not any(prev < b <= cur for b in bind_lines):
                out.append(
                    Finding(
                        rule="JG002", path=module.path, line=cur, col=0,
                        message=(
                            f"PRNG key {name!r} reused by a second "
                            f"sampling call (first use line {prev}) "
                            "without split/fold_in — identical randomness"
                        ),
                    )
                )
    return out


def _assigned_names(node: ast.AST):
    if isinstance(node, ast.Assign):
        for tgt in node.targets:
            for n in ast.walk(tgt):
                if isinstance(n, ast.Name):
                    yield n.id, node.lineno
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) and isinstance(
        node.target, ast.Name
    ):
        yield node.target.id, node.lineno
    elif isinstance(node, ast.For) and isinstance(node.target, ast.Name):
        yield node.target.id, node.lineno


# --------------------------------------------------------------------------
# JG003 — jit-boundary hygiene
# --------------------------------------------------------------------------

_ARRAY_MAKERS = {
    "zeros", "ones", "arange", "asarray", "array", "full", "linspace",
    "eye", "normal", "uniform", "PRNGKey",
}


def _is_train_step_shaped(name: Optional[str], fn: Optional[ast.AST]) -> bool:
    """The shapes we insist donate their input state: a 'step' that is
    explicitly a *train/update* step, or whose first parameter is the
    optimizer-carrying ``state``. Eval steps are excluded — their state
    argument is reused across batches and must NOT be donated.

    Both the jitted binding name AND the resolved callable's own name
    are considered: step builders that jit a shard_map-wrapped body
    (``shmapped = shard_map(compressed_train_step, ...); jax.jit(
    shmapped)``) would otherwise hide a train step behind a wrapper
    binding the name check can't see through — the compressed-DP step
    family is exactly this shape."""
    labels = []
    if name:
        labels.append(name.lower())
    first_param = None
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        labels.append(fn.name.lower())
        if fn.args.args:
            first_param = fn.args.args[0].arg
    if any("eval" in label for label in labels):
        return False
    if not any("step" in label for label in labels):
        return False
    return first_param == "state" or any(
        "train" in label or "update" in label for label in labels
    )


def check_jit_boundary(module: LintModule) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        seg = last_segment(node.func)
        if seg == "jit" and node.args:
            arg = node.args[0]
            arg_name = arg.id if isinstance(arg, ast.Name) else None
            fn = module.resolve_callable(arg)
            kwarg_names = {k.arg for k in node.keywords}
            if (
                _is_train_step_shaped(arg_name, fn)
                and "donate_argnums" not in kwarg_names
                and "donate_argnames" not in kwarg_names
            ):
                out.append(
                    _finding(
                        module, "JG003", node,
                        f"jit of train-step-shaped {arg_name or 'function'!s} "
                        "without donate_argnums — the old state buffer "
                        "stays live, doubling param+opt memory",
                    )
                )
            # non-hashable defaults behind static_argnums/names
            if fn is not None and isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                out.extend(_check_static_hashable(module, node, fn))
        elif seg == "shard_map" and node.args:
            out.extend(_check_shardmap_closure(module, node))
    return out


def _check_static_hashable(
    module: LintModule, call: ast.Call, fn: ast.FunctionDef
) -> List[Finding]:
    out: List[Finding] = []
    params = [a.arg for a in fn.args.args]
    defaults = fn.args.defaults
    default_by_param = dict(zip(params[len(params) - len(defaults):], defaults))
    static: List[str] = []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    static.append(n.value)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    if 0 <= n.value < len(params):
                        static.append(params[n.value])
    for name in static:
        default = default_by_param.get(name)
        if isinstance(default, (ast.List, ast.Dict, ast.Set)):
            out.append(
                _finding(
                    module, "JG003", call,
                    f"static arg {name!r} defaults to an unhashable "
                    f"{type(default).__name__.lower()} — jit static args "
                    "must be hashable (use a tuple/frozenset)",
                )
            )
    return out


def _check_shardmap_closure(module: LintModule, call: ast.Call) -> List[Finding]:
    """Array values captured by a shard_map body from an enclosing
    function become replicated closure constants — usually an unintended
    broadcast (and a silent resharding hazard)."""
    fn = module.resolve_callable(call.args[0])
    if fn is None or isinstance(fn, ast.Lambda):
        body = fn.body if fn is not None else None
        params = {a.arg for a in fn.args.args} if fn is not None else set()
        body_nodes = list(ast.walk(body)) if body is not None else []
    elif isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        params = {a.arg for a in fn.args.args}
        body_nodes = [n for stmt in fn.body for n in ast.walk(stmt)]
    else:
        return []
    if not body_nodes:
        return []
    # names bound from array-creating calls in enclosing function scopes
    array_names: Dict[str, int] = {}
    scope = module.enclosing_scope(fn)
    while not isinstance(scope, ast.Module):
        for name, value in module.scope_assigns.get(scope, {}).items():
            if (
                isinstance(value, ast.Call)
                and last_segment(value.func) in _ARRAY_MAKERS
            ):
                dn = dotted_name(value.func) or ""
                root = dn.split(".")[0]
                if root in ("jnp", "np", "numpy", "jax") or dn.startswith(
                    "jax.random"
                ):
                    array_names.setdefault(name, value.lineno)
        scope = module.enclosing_scope(scope)
    if not array_names:
        return []
    locals_bound = set(params)
    for n in body_nodes:
        for name, _ in _assigned_names(n):
            locals_bound.add(name)
    out = []
    seen = set()
    for n in body_nodes:
        if (
            isinstance(n, ast.Name)
            and isinstance(n.ctx, ast.Load)
            and n.id in array_names
            and n.id not in locals_bound
            and n.id not in seen
        ):
            seen.add(n.id)
            out.append(
                _finding(
                    module, "JG003", n,
                    f"shard_map body closes over array {n.id!r} (built at "
                    f"line {array_names[n.id]}) — closure constants are "
                    "replicated to every device; pass it as an argument "
                    "with an explicit in_spec",
                )
            )
    return out


# --------------------------------------------------------------------------
# JG004 — Python control flow on traced values
# --------------------------------------------------------------------------


def check_tracer_control_flow(module: LintModule) -> List[Finding]:
    out: List[Finding] = []
    for fn in module.traced:
        if isinstance(fn, ast.Lambda):
            continue  # lambdas cannot contain statements
        params = {a.arg for a in fn.args.args}
        params |= {a.arg for a in fn.args.kwonlyargs}
        own_nodes = [
            n for stmt in fn.body for n in ast.walk(stmt)
            if module.nearest_def(n) is fn
        ]
        for n in own_nodes:
            if not isinstance(n, (ast.If, ast.While)):
                continue
            bad = _tracer_names_in_test(n.test, params)
            if bad:
                kind = "if" if isinstance(n, ast.If) else "while"
                out.append(
                    _finding(
                        module, "JG004", n,
                        f"python `{kind}` on traced argument(s) "
                        f"{sorted(bad)} — this branches at trace time "
                        "(ConcretizationTypeError or silent "
                        "specialization); use lax.cond/select, or mark "
                        "the arg static",
                    )
                )
    return out


def _tracer_names_in_test(test: ast.AST, params: set) -> set:
    """Bare parameter names whose runtime *value* steers the branch.
    `x is None`, `isinstance(x, ...)`, and attribute probes like
    `x.ndim == 3` are trace-time-static idioms and excluded."""
    if isinstance(test, ast.Compare):
        ops_static = all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
        )
        if ops_static:
            return set()
    bad = set()
    for n in ast.walk(test):
        if isinstance(n, ast.Call):
            seg = last_segment(n.func)
            if seg in ("isinstance", "len", "getattr", "hasattr", "callable"):
                return set()
        if isinstance(n, ast.Name) and n.id in params:
            parent_attr = False
            # attribute probes (x.ndim / x.shape / x.dtype) are static
            # under jit; walking from the test we can't see parents, so
            # re-scan: a Name that only appears as an Attribute value
            # with a static attr is fine.
            for m in ast.walk(test):
                if (
                    isinstance(m, ast.Attribute)
                    and m.value is n
                    and m.attr in ("shape", "ndim", "dtype", "size", "sharding")
                ):
                    parent_attr = True
            if not parent_attr:
                bad.add(n.id)
    return bad


# --------------------------------------------------------------------------
# JG005 — silent broad except
# --------------------------------------------------------------------------

_LOG_METHODS = {
    "debug", "info", "warning", "warn", "error", "exception", "critical",
}


def check_silent_except(module: LintModule) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        broad = node.type is None or (
            last_segment(node.type) in ("Exception", "BaseException")
        )
        if not broad:
            continue
        body_nodes = [n for stmt in node.body for n in ast.walk(stmt)]
        reraises = any(isinstance(n, ast.Raise) for n in body_nodes)
        logs = any(
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr in _LOG_METHODS
            for n in body_nodes
        )
        uses_exc = node.name is not None and any(
            isinstance(n, ast.Name) and n.id == node.name
            for n in body_nodes
        )
        if not (reraises or logs or uses_exc):
            what = (
                "bare except" if node.type is None
                else f"except {last_segment(node.type)}"
            )
            out.append(
                _finding(
                    module, "JG005", node,
                    f"{what} swallows the error (no re-raise, no logging, "
                    "exception unused) — narrow the type or log why "
                    "ignoring is safe",
                )
            )
    return out


# --------------------------------------------------------------------------
# JG006 — direct jax.shard_map access (version-compat shim exists)
# --------------------------------------------------------------------------


def check_shard_map_compat(module: LintModule) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Attribute):
            dn = dotted_name(node)
            if dn in ("jax.shard_map", "jax.experimental.shard_map"):
                out.append(
                    _finding(
                        module, "JG006", node,
                        f"direct {dn} access breaks across jax versions "
                        "(moved in 0.5, kwarg renamed) — import "
                        "parallel.compat.shard_map instead",
                    )
                )
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            names = (
                [node.module] if isinstance(node, ast.ImportFrom)
                else [a.name for a in node.names]
            )
            for name in names:
                if name and name.startswith("jax.experimental.shard_map"):
                    out.append(
                        _finding(
                            module, "JG006", node,
                            "import of jax.experimental.shard_map — gone "
                            "on newer jax; import "
                            "parallel.compat.shard_map instead",
                        )
                    )
    return out


from ..concurrency.rules import (  # noqa: E402 — after Rule is defined
    check_blocking_in_lock,
    check_callback_in_lock,
    check_check_then_act,
    check_lock_discipline,
    check_wait_predicate,
)

RULES: Dict[str, Rule] = {
    r.id: r
    for r in [
        Rule(
            "JG001", "host-sync-in-trace",
            "float()/np.asarray/.item()/.block_until_ready inside a "
            "jitted / shard_mapped / scanned function",
            check_host_sync,
        ),
        Rule(
            "JG002", "prng-hygiene",
            "hardcoded PRNGKey(literal) in library code; key reuse "
            "across sampling calls without split/fold_in",
            check_prng_hygiene,
        ),
        Rule(
            "JG003", "jit-boundary",
            "train-step jits without donate_argnums; unhashable static "
            "args; shard_map bodies closing over arrays",
            check_jit_boundary,
        ),
        Rule(
            "JG004", "tracer-control-flow",
            "python if/while on traced argument values",
            check_tracer_control_flow,
        ),
        Rule(
            "JG005", "silent-except",
            "broad except that neither re-raises, logs, nor uses the "
            "exception",
            check_silent_except,
        ),
        Rule(
            "JG006", "shard-map-compat",
            "direct jax.shard_map / jax.experimental.shard_map use "
            "instead of the version shim",
            check_shard_map_compat,
        ),
        # Concurrency pack (analysis/concurrency/rules.py): lock
        # discipline for the threaded serving/telemetry stack.
        Rule(
            "JG007", "lock-discipline",
            "guarded attribute (locked writes or '# guarded-by:') read "
            "or written outside its lock in a lock-owning class",
            check_lock_discipline,
        ),
        Rule(
            "JG008", "check-then-act",
            "state checked under a lock but acted on after release and "
            "re-acquisition (TOCTOU window)",
            check_check_then_act,
        ),
        Rule(
            "JG009", "blocking-in-lock",
            "blocking call (IO, sleep, thread join, jitted dispatch, "
            "EventLog.emit) while holding a lock",
            check_blocking_in_lock,
        ),
        Rule(
            "JG010", "callback-in-lock",
            "user/transition callback invoked under a held lock "
            "(reentrancy deadlock hazard)",
            check_callback_in_lock,
        ),
        Rule(
            "JG011", "wait-needs-predicate",
            "untimed Condition.wait() outside a while-predicate loop",
            check_wait_predicate,
        ),
    ]
}
