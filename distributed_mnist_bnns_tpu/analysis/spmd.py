"""Collective-schedule recording and lockstep checking — the runtime
half of the SPMD pack (JG012–JG016 are the static half, in
analysis/lint/rules.py).

The multi-host bug class this guards: a collective executed by some
processes but not others does not error, it **hangs the fleet** — every
participating process blocks in the collective waiting for peers that
never arrive. The source paper's hand-rolled DDP failed exactly this
way (silently, between home machines); ROADMAP item 1's
``jax.distributed`` runtime must not be able to.

How it works, and why eagerly
-----------------------------
Tracing can't catch the bug: ``lax.cond`` traces BOTH branches, so a
collective hidden in one branch shows up in every process's jaxpr and
the schedules look identical even when execution would diverge. Instead
the recorder runs the program **eagerly, once per simulated process**,
under ``jax.disable_jit()`` with every ``jax.lax`` collective (and
``axis_index``) monkeypatched to a shape-correct local stub that logs
``(op, axis, shape, dtype)`` before returning. Under ``disable_jit`` a
``lax.cond`` with a concrete predicate executes only the taken branch
— so per-process predicate divergence yields divergent recorded
schedules, which is precisely the hang condition on real hardware.

The stubs simulate a world of ``world`` processes from the local shard
alone (``psum`` scales by ``world``, ``all_gather`` stacks ``world``
local copies, ``all_to_all`` re-blocks locally, ``axis_index`` returns
the simulated pid). Downstream shapes are exact; values are only
world-plausible — good enough, because the checker compares
**schedules**, not numerics (ops/test_compress.py owns the numerics
against its NumPy oracle).

Entry points
------------
``record_schedule(fn, *args, world=, pid=)`` → ``[CollectiveOp, ...]``
``check_lockstep(schedules)`` → raises :class:`LockstepError` with the
first divergent index when any two processes' schedules differ.
``run_lockstep(build, world)`` — record every pid and check.
``verify_shipped(worlds=(2, 4, 8))`` — the CI ``spmd-lockstep`` job's
body: the compressed-DP exchange, the compressed-FSDP exchange, and
the elastic remesh fold/regrow programs, in lockstep at every world.
``cli lint --spmd`` wraps it.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

__all__ = [
    "CollectiveOp",
    "LockstepError",
    "record_schedule",
    "check_lockstep",
    "run_lockstep",
    "run_lockstep_mesh",
    "verify_shipped",
]

# int worlds simulate a 1-D mesh (the original flat checker); dict
# worlds ({axis_name: size}) simulate a multi-axis mesh — collectives
# then resolve their group size from the NAMED axis they run over
# (tuple axis names multiply member sizes), which is what the two-level
# hierarchical exchange needs: a pmean over 'local' must not scale by
# the 'data' axis's size and vice versa.
World = Any   # int | Dict[str, int]
Pid = Any     # int | Dict[str, int]


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    """One recorded collective: position in the program's schedule plus
    the identity that must match across processes for the op to pair."""

    index: int
    op: str
    axis: Optional[str]
    shape: Tuple[int, ...]
    dtype: str

    def key(self) -> Tuple:
        """What lockstep compares: everything except ``index`` (which
        is implied by position)."""
        return (self.op, self.axis, self.shape, self.dtype)

    def __str__(self) -> str:
        return (
            f"#{self.index} {self.op}(axis={self.axis!r}, "
            f"shape={self.shape}, {self.dtype})"
        )


class LockstepError(RuntimeError):
    """Two simulated processes disagreed on the collective schedule.

    ``divergence_index`` is the first schedule position where any
    process differs from process 0 (length mismatches divergence at the
    shorter schedule's end); ``schedules`` holds every process's full
    recording for the report."""

    def __init__(
        self,
        message: str,
        *,
        divergence_index: int,
        schedules: Sequence[Sequence[CollectiveOp]],
    ) -> None:
        super().__init__(message)
        self.divergence_index = divergence_index
        self.schedules = [list(s) for s in schedules]


def _first_divergence(
    schedules: Sequence[Sequence[CollectiveOp]],
) -> Optional[int]:
    """Index of the first position where any process differs from
    process 0, or None when all schedules agree."""
    base = schedules[0]
    for other in schedules[1:]:
        upto = min(len(base), len(other))
        for i in range(upto):
            if base[i].key() != other[i].key():
                return i
        if len(base) != len(other):
            return upto
    return None


def _divergence_report(
    schedules: Sequence[Sequence[CollectiveOp]], idx: int
) -> str:
    lines = [
        f"collective schedules diverge at index {idx} "
        f"(world {len(schedules)}):"
    ]
    for pid, sched in enumerate(schedules):
        if idx < len(sched):
            entry = str(sched[idx])
        else:
            entry = f"<no collective — schedule ends at {len(sched)}>"
        lines.append(f"  process {pid}: {entry}")
    lo = max(0, idx - 2)
    ctx = schedules[0][lo:idx]
    if ctx:
        lines.append("  last agreed ops: " + "; ".join(str(c) for c in ctx))
    lines.append(
        "  on real multi-host hardware the processes still issuing "
        "collectives would hang waiting for the ones that stopped."
    )
    return "\n".join(lines)


def check_lockstep(schedules: Sequence[Sequence[CollectiveOp]]) -> None:
    """Hard-error with the first divergent index when any two
    processes' schedules differ; no-op when they all agree."""
    if len(schedules) < 2:
        return
    idx = _first_divergence(schedules)
    if idx is not None:
        raise LockstepError(
            _divergence_report(schedules, idx),
            divergence_index=idx,
            schedules=schedules,
        )


# --------------------------------------------------------------------------
# The per-process simulator
# --------------------------------------------------------------------------

_COLLECTIVE_STUBS = (
    "psum", "pmean", "pmax", "pmin", "psum_scatter",
    "all_gather", "all_to_all", "ppermute",
)


def _first_leaf(value: Any):
    import jax

    leaves = jax.tree.leaves(value)
    return leaves[0] if leaves else None


@contextlib.contextmanager
def _simulated_process(
    schedule: List[CollectiveOp], *, world: World, pid: Pid
) -> Iterator[None]:
    """Run the body eagerly as simulated process ``pid`` of ``world``:
    ``jax.lax`` collectives are replaced by recording, shape-correct
    local stubs; ``axis_index`` returns ``pid``; everything runs under
    ``jax.disable_jit()`` so ``lax.cond`` takes only the concrete
    branch (the property the whole checker rests on).

    Multi-axis meshes: pass ``world`` / ``pid`` as ``{axis_name: ...}``
    dicts — each collective then scales/splits by the size of the axis
    (or tuple of axes) it names, and ``axis_index`` returns that axis's
    coordinate."""
    import jax
    import jax.numpy as jnp

    def axis_size(axis: Any) -> int:
        if isinstance(world, int):
            return world
        if axis is None:
            n = 1
            for v in world.values():
                n *= v
            return n
        if isinstance(axis, (tuple, list)):
            n = 1
            for a in axis:
                n *= axis_size(a)
            return n
        return world[axis]

    def axis_pid(axis: Any) -> int:
        if isinstance(pid, int):
            return pid
        if isinstance(axis, (tuple, list)):
            # Row-major flattening over the named axes — the convention
            # a real mesh uses for a collective over a tuple of axes.
            n = 0
            for a in axis:
                n = n * axis_size(a) + axis_pid(a)
            return n
        return pid[axis]

    def record(op: str, axis: Any, value: Any) -> None:
        leaf = _first_leaf(value)
        schedule.append(
            CollectiveOp(
                index=len(schedule),
                op=op,
                axis=None if axis is None else str(axis),
                shape=tuple(getattr(leaf, "shape", ())),
                dtype=str(getattr(leaf, "dtype", "?")),
            )
        )

    def psum(x, axis_name, **kw):
        record("psum", axis_name, x)
        n = axis_size(axis_name)
        return jax.tree.map(lambda v: v * n, x)

    def pmean(x, axis_name, **kw):
        record("pmean", axis_name, x)
        return x

    def pmax(x, axis_name, **kw):
        record("pmax", axis_name, x)
        return x

    def pmin(x, axis_name, **kw):
        record("pmin", axis_name, x)
        return x

    def psum_scatter(x, axis_name, *, scatter_dimension=0, tiled=False, **kw):
        record("psum_scatter", axis_name, x)
        n, i = axis_size(axis_name), axis_pid(axis_name)
        return jax.tree.map(
            lambda v: jnp.split(v * n, n, axis=scatter_dimension)[i],
            x,
        )

    def all_gather(x, axis_name, *, axis=0, tiled=False, **kw):
        record("all_gather", axis_name, x)
        n = axis_size(axis_name)
        if tiled:
            return jax.tree.map(
                lambda v: jnp.concatenate([v] * n, axis=axis), x
            )
        return jax.tree.map(lambda v: jnp.stack([v] * n, axis=axis), x)

    def all_to_all(x, axis_name, split_axis, concat_axis, **kw):
        record("all_to_all", axis_name, x)
        n = axis_size(axis_name)
        return jax.tree.map(
            lambda v: jnp.concatenate(
                jnp.split(v, n, axis=split_axis), axis=concat_axis
            ),
            x,
        )

    def ppermute(x, axis_name, perm, **kw):
        record("ppermute", axis_name, x)
        return x

    def axis_index(axis_name):
        return jnp.int32(axis_pid(axis_name))

    stubs: Dict[str, Callable] = {
        "psum": psum, "pmean": pmean, "pmax": pmax, "pmin": pmin,
        "psum_scatter": psum_scatter, "all_gather": all_gather,
        "all_to_all": all_to_all, "ppermute": ppermute,
        "axis_index": axis_index,
    }
    saved_lax = {name: getattr(jax.lax, name) for name in stubs}
    saved_pi = jax.process_index
    saved_pc = jax.process_count
    try:
        for name, stub in stubs.items():
            setattr(jax.lax, name, stub)
        if isinstance(world, int):
            flat_pid, flat_world = pid, world
        else:
            axes = tuple(world)
            flat_pid = axis_pid(axes)
            flat_world = axis_size(axes)
        jax.process_index = lambda backend=None: flat_pid
        jax.process_count = lambda backend=None: flat_world
        with jax.disable_jit():
            yield
    finally:
        for name, original in saved_lax.items():
            setattr(jax.lax, name, original)
        jax.process_index = saved_pi
        jax.process_count = saved_pc


def record_schedule(
    fn: Callable, *args: Any, world: World, pid: Pid, **kwargs: Any
) -> List[CollectiveOp]:
    """Run ``fn(*args, **kwargs)`` as simulated process ``pid`` of
    ``world`` and return its ordered collective schedule."""
    schedule: List[CollectiveOp] = []
    with _simulated_process(schedule, world=world, pid=pid):
        fn(*args, **kwargs)
    return schedule


def run_lockstep(
    build: Callable[[int, int], Tuple[Callable, Tuple]],
    world: int,
) -> List[List[CollectiveOp]]:
    """Record every simulated process's schedule and lockstep-check
    them. ``build(pid, world)`` returns ``(fn, args)`` — it runs
    OUTSIDE the simulator (host-side setup: seeding per-process data,
    slicing per-process state views), ``fn(*args)`` runs inside.
    Returns the per-process schedules; raises :class:`LockstepError`
    on the first divergence."""
    schedules = []
    for pid in range(world):
        fn, args = build(pid, world)
        schedules.append(record_schedule(fn, *args, world=world, pid=pid))
    check_lockstep(schedules)
    return schedules


def run_lockstep_mesh(
    build: Callable[[Dict[str, int], Dict[str, int]], Tuple[Callable, Tuple]],
    axes: Dict[str, int],
) -> List[List[CollectiveOp]]:
    """Multi-axis :func:`run_lockstep`: record every coordinate of the
    named mesh (row-major over ``axes``) and lockstep-check the lot.
    ``build(pid, axes)`` receives the per-axis coordinate dict — e.g.
    ``{"data": 1, "local": 3}`` on a (data=2, local=4) mesh — and runs
    outside the simulator; ``fn(*args)`` runs inside. On real hardware
    EVERY device participates in every collective of the two-level
    exchange (the local pmean groups by host, the inter-host phases
    group by local index), so all hosts*local schedules must agree."""
    names = tuple(axes)
    coords: List[Dict[str, int]] = [{}]
    for name in names:
        coords = [
            {**c, name: i} for c in coords for i in range(axes[name])
        ]
    schedules = []
    for pid in coords:
        fn, args = build(pid, dict(axes))
        schedules.append(record_schedule(fn, *args, world=dict(axes), pid=pid))
    check_lockstep(schedules)
    return schedules


# --------------------------------------------------------------------------
# The shipped collective programs (the CI spmd-lockstep job's matrix)
# --------------------------------------------------------------------------

_AXIS = "data"
_LOCAL_AXIS = "local"
_N_PARAMS = 1000     # two-leaf pytree, deliberately not bucket-aligned
_BUCKET = 64         # padded = world*nb*64 = 1024 at world 2/4/8
_CHUNKS = 2


def _demo_params():
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(0)
    return {
        "w": jnp.asarray(rng.standard_normal((30, 30)), jnp.float32),
        "b": jnp.asarray(rng.standard_normal((100,)), jnp.float32),
    }


def _demo_grads(pid: int):
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(1234 + pid)
    return {
        "w": jnp.asarray(rng.standard_normal((30, 30)), jnp.float32),
        "b": jnp.asarray(rng.standard_normal((100,)), jnp.float32),
    }


def _local_view(state: Any, world: int, pid: int) -> Any:
    """The shard_map-local view of exchange state: every leaf carrying
    the leading ``world`` axis is sliced to this process's row (kept as
    a leading axis of 1, exactly what the in-specs produce)."""
    import jax

    def slice_leaf(leaf):
        if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == world:
            return leaf[pid:pid + 1]
        return leaf

    return jax.tree.map(slice_leaf, state)


def _dp_program(world: int):
    """The compressed-DP exchange: ``sign_compress`` (two-phase 1-bit
    all-reduce with double error feedback) as each process runs it
    inside the shard_map step."""
    from ..train.optim import sign_compress

    tx = sign_compress(
        mode="sign_ef", world=world, axis_name=_AXIS,
        bucket_size=_BUCKET, chunks=_CHUNKS,
    )
    state = tx.init(_demo_params())

    def build(pid: int, w: int):
        return tx.update, (_demo_grads(pid), _local_view(state, w, pid))

    return build


def _fsdp_program(world: int):
    """The compressed-FSDP/ZeRO exchange: ``sign_compress_fsdp`` with a
    sharded adam inner — reduce-scatter, owner update, compressed
    all-gather of the delta."""
    import optax

    from ..train.optim import sign_compress_fsdp

    params = _demo_params()
    tx = sign_compress_fsdp(
        optax.adam(1e-3), mode="sign_ef", world=world, axis_name=_AXIS,
        bucket_size=_BUCKET, chunks=_CHUNKS,
    )
    state = tx.init(params)

    def build(pid: int, w: int):
        return (
            tx.update,
            (_demo_grads(pid), _local_view(state, w, pid), params),
        )

    return build


def _remesh_program(world: int):
    """The elastic remesh program: FSDP exchange state initialized at a
    DIFFERENT origin world, re-placed onto ``world`` by
    ``parallel.remesh.remesh_compress_state`` (fold when shrinking,
    regrow when growing), then one exchange step at the new world —
    the post-remesh step every elastic resize immediately runs."""
    import optax

    from ..ops.comm_compress import make_plan, tree_size
    from ..parallel.remesh import remesh_compress_state
    from ..train.optim import sign_compress_fsdp

    origin = 8 if world in (2, 4) else 4
    params = _demo_params()
    tx_origin = sign_compress_fsdp(
        optax.adam(1e-3), mode="sign_ef", world=origin, axis_name=_AXIS,
        bucket_size=_BUCKET, chunks=_CHUNKS,
    )
    origin_state = tx_origin.init(params)
    plan = make_plan(
        tree_size(params), world=world, mode="sign_ef",
        bucket_size=_BUCKET, chunks=_CHUNKS, layout="fsdp",
    )
    remeshed, replaced = remesh_compress_state(origin_state, plan)
    if replaced == 0:
        raise RuntimeError(
            f"remesh {origin}->{world} replaced no state nodes — the "
            "lockstep program is not exercising the fold/regrow path"
        )
    tx = sign_compress_fsdp(
        optax.adam(1e-3), mode="sign_ef", world=world, axis_name=_AXIS,
        bucket_size=_BUCKET, chunks=_CHUNKS,
    )

    def build(pid: int, w: int):
        return (
            tx.update,
            (_demo_grads(pid), _local_view(remeshed, w, pid), params),
        )

    return build


def _hier_program(hosts: int, local: int):
    """The two-level hierarchical exchange: fp32 pmean over 'local'
    (the in-host ring) then ``sign_compress``'s 1-bit two-phase
    exchange over 'data' (the inter-host link), as each DEVICE of the
    (hosts x local) mesh runs it inside the hierarchical shard_map
    step. Per-host EF rows are replicated over 'local', so the local
    view slices the leading ``hosts`` axis by the 'data' coordinate."""
    from ..train.optim import sign_compress

    tx = sign_compress(
        mode="sign_ef", world=hosts, axis_name=_AXIS,
        local_axis_name=_LOCAL_AXIS, bucket_size=_BUCKET, chunks=_CHUNKS,
    )
    state = tx.init(_demo_params())

    def build(pid: Dict[str, int], axes: Dict[str, int]):
        flat = pid[_AXIS] * axes[_LOCAL_AXIS] + pid[_LOCAL_AXIS]
        return tx.update, (
            _demo_grads(flat), _local_view(state, hosts, pid[_AXIS]),
        )

    return build


SHIPPED_PROGRAMS: Dict[str, Callable[[int], Callable]] = {
    "dp_exchange": _dp_program,
    "fsdp_exchange": _fsdp_program,
    "remesh_fold_regrow": _remesh_program,
}

# Multi-axis programs run at (hosts x local) meshes instead of flat
# worlds: every process x local-device coordinate is simulated and must
# agree on the full two-level schedule.
SHIPPED_MESH_PROGRAMS: Dict[str, Callable[[int, int], Callable]] = {
    "hier_exchange": _hier_program,
}

MESH_WORLDS: Tuple[Tuple[int, int], ...] = ((2, 2), (2, 4), (4, 2))


def verify_shipped(
    worlds: Sequence[int] = (2, 4, 8),
    programs: Optional[Sequence[str]] = None,
) -> List[Dict[str, Any]]:
    """Lockstep-check every shipped collective program at every world.

    Returns one report row per (program, world):
    ``{"program", "world", "n_collectives", "ok": True}``. Raises
    :class:`LockstepError` (with the offending program named in the
    message) on the first divergence — this is the CI ``spmd-lockstep``
    job's body and the gate ROADMAP item 1's multi-host PR must pass.
    """
    if programs is not None:
        names = list(programs)
    else:
        names = list(SHIPPED_PROGRAMS) + list(SHIPPED_MESH_PROGRAMS)
    report: List[Dict[str, Any]] = []
    for name in names:
        if name in SHIPPED_PROGRAMS:
            factory = SHIPPED_PROGRAMS[name]
            for world in worlds:
                try:
                    schedules = run_lockstep(factory(world), world)
                except LockstepError as e:
                    raise LockstepError(
                        f"program {name!r} at world {world}:\n{e}",
                        divergence_index=e.divergence_index,
                        schedules=e.schedules,
                    ) from None
                report.append(
                    {
                        "program": name,
                        "world": world,
                        "n_collectives": len(schedules[0]),
                        "ok": True,
                    }
                )
            continue
        factory = SHIPPED_MESH_PROGRAMS[name]
        for hosts, local in MESH_WORLDS:
            axes = {_AXIS: hosts, _LOCAL_AXIS: local}
            try:
                schedules = run_lockstep_mesh(factory(hosts, local), axes)
            except LockstepError as e:
                raise LockstepError(
                    f"program {name!r} at mesh {hosts}x{local}:\n{e}",
                    divergence_index=e.divergence_index,
                    schedules=e.schedules,
                ) from None
            report.append(
                {
                    "program": name,
                    "world": f"{hosts}x{local}",
                    "n_collectives": len(schedules[0]),
                    "ok": True,
                }
            )
    return report
