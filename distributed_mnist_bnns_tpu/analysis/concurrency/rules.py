"""Concurrency lint rules JG007-JG011 — lock discipline for the
serving/telemetry stack.

Every threading bug this repo has shipped (EventLog's unlocked writes,
the drain busy-flag TOCTOU, the submit-vs-``_cancel_all`` stranded
enqueue) was found by a human reviewer; these rules encode the shapes so
the linter finds the next one. Like the JG001-JG006 pack they are pure
AST analysis over one :class:`~..lint.core.LintModule` — no imports of
the code under analysis, deliberately conservative.

The unit of analysis is the **lock-owning class**: a class that binds
``self.<name> = threading.Lock() / RLock() / Condition()``. Owning a
lock is the evidence of concurrency — once a class has one, *every*
method is treated as a potentially concurrent context (a superset of
"reachable from a spawned ``threading.Thread`` target": worker ``_run``
loops, HTTP handler entry points and drain paths are all plain methods
here, and a lock-owning class whose methods were all single-threaded
would not need the lock).

Guarded-attribute inference (JG007): an attribute written at least once
while holding lock ``L`` (direct assignment, augmented/subscript store,
or a mutating method call like ``.append``/``.popleft``) is considered
guarded by ``L``. Two annotation comments extend/override inference:

    self._slots = []          # guarded-by: _cond
        declares the attribute guarded even when inference can't see a
        locked write (e.g. all writes funnel through a helper);

    def _set(self, new):      # holds-lock: _lock
        declares that every caller holds ``_lock``, so the body is
        analyzed as lock-held (the classic "lock held by caller"
        helper). Also accepted on the line directly above the ``def``.

Accesses inside nested ``def``/``lambda`` bodies are skipped entirely:
a closure may run on any thread at any time, and guessing produces
exactly the false positives that get a rule suppressed wholesale.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from ..lint.core import Finding, LintModule, dotted_name, last_segment

GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*(?P<lock>\w+)")
HOLDS_LOCK_RE = re.compile(r"#\s*holds-lock:\s*(?P<lock>\w+)")

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

#: method calls on an attribute that mutate it in place — writes for the
#: purposes of guarded-set inference and outside-lock detection.
MUTATING_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "remove", "discard", "add", "clear", "update",
    "setdefault", "sort", "reverse", "put", "put_nowait",
}


def _finding(module: LintModule, rule_id: str, node: ast.AST, msg: str) -> Finding:
    return Finding(
        rule=rule_id,
        path=module.path,
        line=getattr(node, "lineno", 0),
        col=getattr(node, "col_offset", 0),
        message=msg,
    )


@dataclasses.dataclass
class _Access:
    """One ``self.<attr>`` touch inside a method body."""

    node: ast.AST
    attr: str
    write: bool
    held: FrozenSet[str]
    method: str


@dataclasses.dataclass
class _LockRegion:
    """One ``with self.<lock>:`` statement, with the class attributes it
    reads/writes (used by JG008's cross-release pairing)."""

    node: ast.With
    lock: str
    reads: Set[str]
    writes: Set[str]


class ClassLockInfo:
    """Lock ownership + guarded-attribute analysis of one class."""

    def __init__(self, module: LintModule, cls: ast.ClassDef):
        self.module = module
        self.cls = cls
        self.locks: Dict[str, str] = {}       # attr -> Lock|RLock|Condition
        self.annotated: Dict[str, Set[str]] = {}   # lock -> attrs
        self.holds: Dict[str, Set[str]] = {}       # method name -> locks
        self.accesses: List[_Access] = []
        self.regions: Dict[str, List[_LockRegion]] = {}  # method -> regions
        #: every Call executed with >=1 owned lock held (JG009/JG010)
        self.held_calls: List[
            Tuple[ast.FunctionDef, ast.Call, FrozenSet[str]]
        ] = []
        self._find_locks()
        if self.locks:
            self._find_annotations()
            self._collect()
            self.guarded = self._infer_guarded()

    # -- discovery ----------------------------------------------------------

    def _methods(self) -> Iterable[ast.FunctionDef]:
        for node in self.cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    def _find_locks(self) -> None:
        for node in ast.walk(self.cls):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            tgt = node.targets[0]
            if not (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                continue
            value = node.value
            if (
                isinstance(value, ast.Call)
                and last_segment(value.func) in LOCK_FACTORIES
            ):
                self.locks[tgt.attr] = last_segment(value.func)

    def _find_annotations(self) -> None:
        lines = self.module.lines
        for node in ast.walk(self.cls):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                    and node.lineno <= len(lines)
                ):
                    m = GUARDED_BY_RE.search(lines[node.lineno - 1])
                    if m:
                        self.annotated.setdefault(
                            m.group("lock"), set()
                        ).add(tgt.attr)
        for fn in self._methods():
            held: Set[str] = set()
            for lineno in (fn.lineno, fn.lineno - 1):
                if 1 <= lineno <= len(lines):
                    m = HOLDS_LOCK_RE.search(lines[lineno - 1])
                    if m:
                        held.add(m.group("lock"))
            if held:
                self.holds[fn.name] = held

    # -- access walk --------------------------------------------------------

    def _with_locks(self, node: ast.With) -> Set[str]:
        out: Set[str] = set()
        for item in node.items:
            expr = item.context_expr
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and expr.attr in self.locks
            ):
                out.add(expr.attr)
        return out

    def _classify_access(self, node: ast.Attribute) -> Optional[bool]:
        """True=write, False=read, None=not a state access (the lock
        itself, or a plain ``self.method(...)`` call)."""
        if node.attr in self.locks:
            return None
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            return True
        parent = self.module.parents.get(node)
        if isinstance(parent, ast.Subscript) and parent.value is node:
            if isinstance(parent.ctx, (ast.Store, ast.Del)):
                return True
            return False
        if isinstance(parent, ast.Attribute) and parent.value is node:
            gp = self.module.parents.get(parent)
            if (
                isinstance(gp, ast.Call)
                and gp.func is parent
                and parent.attr in MUTATING_METHODS
            ):
                return True
            return False
        if isinstance(parent, ast.Call) and parent.func is node:
            # self.method(...) — a bound-method call, not state access
            return None
        return False

    def _collect(self) -> None:
        for fn in self._methods():
            base = frozenset(self.holds.get(fn.name, set()))
            regions: List[_LockRegion] = []

            def walk(node: ast.AST, held: FrozenSet[str],
                     region: Optional[_LockRegion]) -> None:
                for child in ast.iter_child_nodes(node):
                    if isinstance(
                        child,
                        (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                    ):
                        continue  # closures: unknown thread/lock context
                    child_held, child_region = held, region
                    if isinstance(child, ast.With):
                        locks = self._with_locks(child)
                        if locks:
                            child_held = held | locks
                            child_region = _LockRegion(
                                child, sorted(locks)[0], set(), set()
                            )
                            regions.append(child_region)
                    if (
                        isinstance(child, ast.Attribute)
                        and isinstance(child.value, ast.Name)
                        and child.value.id == "self"
                    ):
                        write = self._classify_access(child)
                        if write is not None:
                            self.accesses.append(_Access(
                                child, child.attr, write, child_held,
                                fn.name,
                            ))
                            if child_region is not None:
                                (child_region.writes if write
                                 else child_region.reads).add(child.attr)
                    if isinstance(child, ast.Call) and child_held:
                        self.held_calls.append((fn, child, child_held))
                    walk(child, child_held, child_region)

            walk(fn, base, None)
            self.regions[fn.name] = regions

    def _infer_guarded(self) -> Dict[str, Set[str]]:
        guarded: Dict[str, Set[str]] = {
            lock: set(attrs) for lock, attrs in self.annotated.items()
        }
        for acc in self.accesses:
            if acc.write and acc.method != "__init__":
                for lock in acc.held:
                    guarded.setdefault(lock, set()).add(acc.attr)
        return guarded


def _lock_classes(module: LintModule) -> List[ClassLockInfo]:
    """Lock-owning classes of ``module``, analyzed once and cached on
    the module (five rules consume the same per-class analysis)."""
    cached = getattr(module, "_concurrency_lock_classes", None)
    if cached is None:
        cached = [
            info
            for node in ast.walk(module.tree)
            if isinstance(node, ast.ClassDef)
            for info in [ClassLockInfo(module, node)]
            if info.locks
        ]
        module._concurrency_lock_classes = cached  # type: ignore[attr-defined]
    return cached


# --------------------------------------------------------------------------
# JG007 — guarded attribute accessed outside its lock
# --------------------------------------------------------------------------


def check_lock_discipline(module: LintModule) -> List[Finding]:
    out: List[Finding] = []
    for info in _lock_classes(module):
        for acc in info.accesses:
            if acc.method == "__init__":
                continue
            owners = sorted(
                lock for lock, attrs in info.guarded.items()
                if acc.attr in attrs
            )
            if not owners:
                continue
            if any(lock in acc.held for lock in owners):
                continue
            what = "write to" if acc.write else "read of"
            out.append(_finding(
                module, "JG007", acc.node,
                f"{what} {info.cls.name}.{acc.attr} outside "
                f"'with self.{owners[0]}:' — the attribute is guarded by "
                f"{'/'.join(owners)} (locked writes elsewhere, or a "
                "'# guarded-by:' annotation); hold the lock, or mark the "
                "helper '# holds-lock: <lock>' if every caller already "
                "does",
            ))
    return out


# --------------------------------------------------------------------------
# JG008 — check-then-act across a lock release (TOCTOU)
# --------------------------------------------------------------------------


def check_check_then_act(module: LintModule) -> List[Finding]:
    out: List[Finding] = []
    for info in _lock_classes(module):
        for method, regions in info.regions.items():
            for i, first in enumerate(regions):
                if first.writes or not first.reads:
                    continue  # the check region must be read-only
                for later in regions[i + 1:]:
                    if later.lock != first.lock:
                        continue
                    if later.node.lineno <= first.node.lineno:
                        continue
                    if not later.writes:
                        continue
                    racy = sorted(first.reads & later.writes)
                    if racy:
                        out.append(_finding(
                            module, "JG008", later.node,
                            f"{info.cls.name}.{method} checks "
                            f"{', '.join(racy)} under self.{first.lock} "
                            f"(line {first.node.lineno}) but acts on it "
                            "here after the lock was released and "
                            "re-acquired — another thread can invalidate "
                            "the check in between; do the check and the "
                            "act under ONE acquisition",
                        ))
                        continue
                    # cross-attribute TOCTOU (the PR 4 drain busy-flag
                    # and PR 6 stranded-enqueue shape): the act region
                    # mutates OTHER state without re-reading any of the
                    # checked attributes — the check it is predicated
                    # on was stale by acquisition time. A region that
                    # re-reads (or rewrites) the checked attrs is the
                    # shipped recheck-in-the-acting-acquisition fix.
                    unchecked = sorted(
                        first.reads - later.reads - later.writes
                    )
                    if len(unchecked) == len(first.reads):
                        out.append(_finding(
                            module, "JG008", later.node,
                            f"{info.cls.name}.{method} checks "
                            f"{', '.join(unchecked)} under "
                            f"self.{first.lock} (line "
                            f"{first.node.lineno}) but writes "
                            f"{', '.join(sorted(later.writes))} here in "
                            "a LATER acquisition without re-checking — "
                            "another thread can invalidate the check "
                            "between the two critical sections; "
                            "re-check the predicate in the acquisition "
                            "that acts on it",
                        ))
    return out


# --------------------------------------------------------------------------
# JG009 — blocking call while holding a lock
# --------------------------------------------------------------------------

_SOCKET_METHODS = {"recv", "recv_into", "sendall", "accept", "connect"}
_FILE_METHODS = {"read", "readline", "readlines", "write", "writelines",
                 "flush"}
_FILE_RECEIVERS = {"_fh", "fh", "f", "fp", "file", "wfile", "rfile",
                   "sock", "conn"}
_DEVICE_SYNCS = {"block_until_ready", "device_get"}


def _blocking_reason(node: ast.Call) -> Optional[str]:
    func = node.func
    dn = dotted_name(func) or ""
    seg = last_segment(func)
    if dn == "time.sleep" or dn == "sleep":
        return "time.sleep blocks every thread contending for the lock"
    if isinstance(func, ast.Name) and func.id == "open":
        return "file open is blocking IO"
    if dn.startswith("subprocess."):
        return "subprocess calls block on the child"
    if seg in _DEVICE_SYNCS:
        return f".{seg}() is a device sync — an unbounded stall under " \
               "a contended lock"
    if not isinstance(func, ast.Attribute):
        return None
    recv = last_segment(func.value) or ""
    recv_chain = (dotted_name(func.value) or recv).lower()
    if seg in _SOCKET_METHODS:
        return f"socket .{seg}() blocks on the peer"
    if seg == "join" and ("thread" in recv_chain or "proc" in recv_chain):
        return "joining a thread while holding a lock deadlocks if that " \
               "thread needs the lock to exit"
    if seg in _FILE_METHODS and (
        recv in _FILE_RECEIVERS or "sock" in recv_chain
    ):
        return f"file/socket .{seg}() is blocking IO"
    if seg == "emit" and (
        "telemetry" in recv_chain or "log" in recv_chain
        or "event" in recv_chain
    ):
        return "EventLog.emit does file IO under its own lock — " \
               "IO latency and lock nesting leak into every waiter"
    if seg in ("decode", "prefill") and "decoder" in recv_chain:
        return f"jitted .{seg}() dispatch can stall on XLA/device time"
    if seg.endswith("_fn"):
        return f"{seg}() looks like a jitted dispatch — device time " \
               "under a lock stalls every waiter"
    return None


def check_blocking_in_lock(module: LintModule) -> List[Finding]:
    out: List[Finding] = []
    for info in _lock_classes(module):
        for _fn, node, held in info.held_calls:
            reason = _blocking_reason(node)
            if reason is not None:
                out.append(_finding(
                    module, "JG009", node,
                    f"blocking call while holding self.{sorted(held)[0]}: "
                    f"{reason}; move it outside the critical section "
                    "(snapshot under the lock, act after release)",
                ))
    return out


# --------------------------------------------------------------------------
# JG010 — user callback invoked under a held lock
# --------------------------------------------------------------------------

_CALLBACK_NAMES = {"callback", "cb", "hook"}


def _callback_reason(
    node: ast.Call, params: Set[str]
) -> Optional[str]:
    func = node.func
    seg = last_segment(func) or ""
    if seg.startswith(("on_", "_on_")):
        return f"{seg} is a transition/user callback"
    if seg in _CALLBACK_NAMES or seg.endswith(("_callback", "_hook")):
        return f"{seg} is a callback"
    if isinstance(func, ast.Name) and func.id in params:
        return f"{func.id} is a caller-supplied callable (parameter)"
    return None


def check_callback_in_lock(module: LintModule) -> List[Finding]:
    out: List[Finding] = []
    for info in _lock_classes(module):
        params_by_fn: Dict[str, Set[str]] = {}
        for fn, node, held in info.held_calls:
            params = params_by_fn.get(fn.name)
            if params is None:
                params = {a.arg for a in fn.args.args} - {"self"}
                params |= {a.arg for a in fn.args.kwonlyargs}
                params_by_fn[fn.name] = params
            reason = _callback_reason(node, params)
            if reason is not None:
                out.append(_finding(
                    module, "JG010", node,
                    f"{reason}, invoked while holding "
                    f"self.{sorted(held)[0]} — a "
                    "callback that re-enters this object "
                    "deadlocks (non-reentrant lock) or sees "
                    "half-updated state; capture it under "
                    "the lock, call it after release (see "
                    "CircuitBreaker._set's deferred-notify "
                    "pattern)",
                ))
    return out


# --------------------------------------------------------------------------
# JG011 — untimed Condition.wait outside a while-predicate loop
# --------------------------------------------------------------------------


def _wait_is_untimed(node: ast.Call) -> bool:
    """Bare ``wait()``, ``wait(None)`` and ``wait(timeout=None)`` are
    all untimed; anything else (a real timeout expression) is treated
    as a bounded poll and exempted."""
    if not node.args and not node.keywords:
        return True
    timeout: Optional[ast.expr] = node.args[0] if node.args else None
    for kw in node.keywords:
        if kw.arg == "timeout":
            timeout = kw.value
    return isinstance(timeout, ast.Constant) and timeout.value is None


def check_wait_predicate(module: LintModule) -> List[Finding]:
    out: List[Finding] = []
    # condition attrs per class (receiver ``self.<c>``); plus any
    # receiver whose name says "cond".
    cond_attrs: Set[Tuple[ast.ClassDef, str]] = set()
    for info in _lock_classes(module):
        for attr, kind in info.locks.items():
            if kind == "Condition":
                cond_attrs.add((info.cls, attr))
    for node in ast.walk(module.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "wait"
            and _wait_is_untimed(node)
        ):
            continue
        recv = node.func.value
        recv_name = (last_segment(recv) or "").lower()
        is_cond = "cond" in recv_name
        if (
            not is_cond
            and isinstance(recv, ast.Attribute)
            and isinstance(recv.value, ast.Name)
            and recv.value.id == "self"
        ):
            is_cond = any(attr == recv.attr for _, attr in cond_attrs)
        if not is_cond:
            continue
        # walk up: a While between the call and the enclosing function
        # means the predicate is (presumably) rechecked after wakeup
        cur = module.parents.get(node)
        in_while = False
        while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            if isinstance(cur, ast.While):
                in_while = True
                break
            cur = module.parents.get(cur)
        if not in_while:
            out.append(_finding(
                module, "JG011", node,
                "untimed Condition.wait() outside a while-predicate "
                "loop — spurious wakeups and missed notifies are legal, "
                "so the state must be rechecked: "
                "`while not pred: cond.wait()` or cond.wait_for(pred). "
                "(Timed waits are exempt: they are bounded polls.)",
            ))
    return out
