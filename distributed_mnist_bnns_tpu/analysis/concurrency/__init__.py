"""Concurrency lint rules (JG007-JG011) — see ANALYSIS.md and rules.py.

The rules plug into the ``analysis/lint`` engine through the shared
``RULES`` registry (lint/rules.py imports this package); the runtime
half — instrumented locks + the seeded interleaving scheduler — lives
in ``analysis/sched.py``.
"""

from .rules import (
    ClassLockInfo,
    check_blocking_in_lock,
    check_callback_in_lock,
    check_check_then_act,
    check_lock_discipline,
    check_wait_predicate,
)

__all__ = [
    "ClassLockInfo",
    "check_blocking_in_lock",
    "check_callback_in_lock",
    "check_check_then_act",
    "check_lock_discipline",
    "check_wait_predicate",
]
