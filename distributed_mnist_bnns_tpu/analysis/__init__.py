"""Static analysis + runtime sanitizers for JAX footguns.

Four halves (ANALYSIS.md is the user-facing catalog):

* ``analysis.lint`` — an AST linter with repo-tailored rules: the JAX
  pack (JG001-JG006: host syncs inside traced functions, PRNG-key
  hygiene, jit-boundary hygiene, python control flow on tracers, silent
  broad excepts, direct ``jax.shard_map`` use bypassing the version
  shim), the concurrency pack (JG007-JG011,
  ``analysis/concurrency/``: lock discipline, check-then-act TOCTOU,
  blocking calls / user callbacks under a held lock, ``Condition.wait``
  without a predicate loop), and the SPMD pack (JG012-JG016:
  collectives under data-dependent control flow, unbound axis names,
  cross-branch collective-order mismatches, donation use-after-donate,
  shard_map spec-arity mismatches) plus the event-schema contracts
  (JG017/JG018 against ``obs/events.py``'s ``EVENT_KINDS`` registry).
  Run it via ``python -m distributed_mnist_bnns_tpu.cli lint``; CI
  fails on any unsuppressed finding.

* ``analysis.guards`` — opt-in runtime ``Sanitizer``: a recompile fence
  (obs/recompile counts over budget become hard errors), a transfer
  guard (``jax.transfer_guard('disallow')`` around the jitted step), and
  a NaN/inf fence on the loss. Threaded through ``TrainConfig.sanitize``
  and the ``JG_SANITIZE`` env var (how CI runs tier-1).

* ``analysis.sched`` — the concurrency pack's runtime half: a lock →
  attribute trace recorder that corroborates JG007 findings against
  actual executions, and a seeded cooperative scheduler that replays
  adversarial interleavings deterministically (the race-regression
  harness in tests/test_concurrency.py).

* ``analysis.spmd`` — the SPMD pack's runtime half: a per-simulated-
  process collective-schedule recorder (eager execution with stubbed
  ``jax.lax`` collectives, so ``lax.cond`` takes only the concrete
  branch) and a lockstep checker that hard-errors with the first
  divergent index when any two processes' schedules differ. Wired into
  ``cli lint --spmd`` and the CI ``spmd-lockstep`` job; the gate the
  multi-host runtime (ROADMAP item 1) must pass.
"""

from .guards import (
    NaNFenceError,
    RecompileFenceError,
    Sanitizer,
    SanitizerConfig,
    SanitizerError,
)
from .sched import (
    CoopScheduler,
    DeadlockError,
    InstrumentedCondition,
    InstrumentedLock,
    TraceRecorder,
    watch_attrs,
)
from .spmd import (
    CollectiveOp,
    LockstepError,
    check_lockstep,
    record_schedule,
    run_lockstep,
    verify_shipped,
)

__all__ = [
    "CollectiveOp",
    "CoopScheduler",
    "DeadlockError",
    "InstrumentedCondition",
    "InstrumentedLock",
    "LockstepError",
    "NaNFenceError",
    "RecompileFenceError",
    "Sanitizer",
    "SanitizerConfig",
    "SanitizerError",
    "TraceRecorder",
    "check_lockstep",
    "record_schedule",
    "run_lockstep",
    "verify_shipped",
    "watch_attrs",
]
