"""Static analysis + runtime sanitizers for JAX footguns.

Three halves (ANALYSIS.md is the user-facing catalog):

* ``analysis.lint`` — an AST linter with repo-tailored rules: the JAX
  pack (JG001-JG006: host syncs inside traced functions, PRNG-key
  hygiene, jit-boundary hygiene, python control flow on tracers, silent
  broad excepts, direct ``jax.shard_map`` use bypassing the version
  shim) and the concurrency pack (JG007-JG011,
  ``analysis/concurrency/``: lock discipline, check-then-act TOCTOU,
  blocking calls / user callbacks under a held lock, ``Condition.wait``
  without a predicate loop). Run it via
  ``python -m distributed_mnist_bnns_tpu.cli lint``; CI fails on any
  unsuppressed finding.

* ``analysis.guards`` — opt-in runtime ``Sanitizer``: a recompile fence
  (obs/recompile counts over budget become hard errors), a transfer
  guard (``jax.transfer_guard('disallow')`` around the jitted step), and
  a NaN/inf fence on the loss. Threaded through ``TrainConfig.sanitize``
  and the ``JG_SANITIZE`` env var (how CI runs tier-1).

* ``analysis.sched`` — the concurrency pack's runtime half: a lock →
  attribute trace recorder that corroborates JG007 findings against
  actual executions, and a seeded cooperative scheduler that replays
  adversarial interleavings deterministically (the race-regression
  harness in tests/test_concurrency.py).
"""

from .guards import (
    NaNFenceError,
    RecompileFenceError,
    Sanitizer,
    SanitizerConfig,
    SanitizerError,
)
from .sched import (
    CoopScheduler,
    DeadlockError,
    InstrumentedCondition,
    InstrumentedLock,
    TraceRecorder,
    watch_attrs,
)

__all__ = [
    "CoopScheduler",
    "DeadlockError",
    "InstrumentedCondition",
    "InstrumentedLock",
    "NaNFenceError",
    "RecompileFenceError",
    "Sanitizer",
    "SanitizerConfig",
    "SanitizerError",
    "TraceRecorder",
    "watch_attrs",
]
