"""Static analysis + runtime sanitizers for JAX footguns.

Two halves (ANALYSIS.md is the user-facing catalog):

* ``analysis.lint`` — an AST linter with repo-tailored rules
  (JG001-JG006): host syncs inside traced functions, PRNG-key hygiene,
  jit-boundary hygiene (donation, static-arg hashability, shard_map
  closures), python control flow on tracers, silent broad excepts, and
  direct ``jax.shard_map`` use bypassing the version shim. Run it via
  ``python -m distributed_mnist_bnns_tpu.cli lint``; CI fails on any
  unsuppressed finding.

* ``analysis.guards`` — opt-in runtime ``Sanitizer``: a recompile fence
  (obs/recompile counts over budget become hard errors), a transfer
  guard (``jax.transfer_guard('disallow')`` around the jitted step), and
  a NaN/inf fence on the loss. Threaded through ``TrainConfig.sanitize``
  and the ``JG_SANITIZE`` env var (how CI runs tier-1).
"""

from .guards import (
    NaNFenceError,
    RecompileFenceError,
    Sanitizer,
    SanitizerConfig,
    SanitizerError,
)

__all__ = [
    "NaNFenceError",
    "RecompileFenceError",
    "Sanitizer",
    "SanitizerConfig",
    "SanitizerError",
]
