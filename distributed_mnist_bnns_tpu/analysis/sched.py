"""Runtime half of the concurrency pack (ANALYSIS.md "Concurrency"):
instrumented locks that record lock→attribute access traces, and a
seeded cooperative scheduler that forces adversarial interleavings.

The static rules (JG007-JG011, analysis/concurrency/rules.py) reason
about *possible* executions; this module pins down *actual* ones:

* :class:`TraceRecorder` + :class:`InstrumentedLock` /
  :class:`InstrumentedCondition` + :func:`watch_attrs` — wrap a class's
  locks and shared attributes in tests, run the real workload, and the
  recorder holds the per-thread trace of which locks were held at every
  attribute touch. :meth:`TraceRecorder.guarded_violations` then applies
  JG007's inference rule (an attribute written at least once under lock
  L is guarded by L) to the *observed* trace — corroborating or
  refuting a static finding with ground truth.

* :class:`CoopScheduler` — a seeded cooperative scheduler for
  deterministic race reproduction. Threads registered through
  :meth:`spawn` run ONE at a time; at every yield point (explicit
  ``sched.yield_point()`` calls patched into a mutant, plus the
  acquire/release/blocked edges of every instrumented lock bound to the
  scheduler) the seeded RNG picks which thread proceeds. A race that a
  stress test hits once a week becomes ``reproduces(seed=N)``: replay
  the same seed, get the same interleaving, every time. Lock
  acquisition under the scheduler is non-blocking-with-reschedule, so
  serializing the threads cannot deadlock on a held lock — the holder
  just gets scheduled until it releases.

Nothing here imports jax and nothing is armed in production code paths:
tests opt in by constructing the objects (see
tests/test_concurrency.py, the two historical-race regressions).
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Set, Tuple

__all__ = [
    "AccessEvent",
    "CoopScheduler",
    "DeadlockError",
    "InstrumentedCondition",
    "InstrumentedLock",
    "TraceRecorder",
    "watch_attrs",
]


# --------------------------------------------------------------------------
# lock→attribute tracing
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AccessEvent:
    """One recorded event: a lock edge or an attribute touch."""

    thread: str
    kind: str                 # acquire | release | wait | notify | read | write
    name: str                 # lock name, or attribute name
    held: Tuple[str, ...]     # locks held by the thread at the event
    seq: int                  # global order


class TraceRecorder:
    """Collects :class:`AccessEvent` records from instrumented locks and
    watched attributes, with the per-thread held-lock set maintained
    here so a watched attribute access knows its lock context."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.events: List[AccessEvent] = []
        self._held = threading.local()

    # -- held-lock bookkeeping (called by the instrumented locks) -----------

    def _held_now(self) -> List[str]:
        held = getattr(self._held, "names", None)
        if held is None:
            held = self._held.names = []
        return held

    def record(self, kind: str, name: str) -> AccessEvent:
        held = self._held_now()
        with self._lock:
            ev = AccessEvent(
                thread=threading.current_thread().name,
                kind=kind, name=name, held=tuple(held),
                seq=len(self.events),
            )
            self.events.append(ev)
        if kind == "acquire":
            held.append(name)
        elif kind == "release":
            if name in held:
                held.remove(name)
        return ev

    # -- queries -------------------------------------------------------------

    def snapshot(self) -> List[AccessEvent]:
        """Point-in-time copy of the trace (queries must not iterate
        ``events`` while instrumented threads are still appending)."""
        with self._lock:
            return list(self.events)

    def accesses(self, attr: Optional[str] = None) -> List[AccessEvent]:
        return [
            e for e in self.snapshot()
            if e.kind in ("read", "write")
            and (attr is None or e.name == attr)
        ]

    def inferred_guards(self) -> Dict[str, Set[str]]:
        """JG007's inference applied to the observed trace: attribute ->
        locks that were held at EVERY write (an attribute never written,
        or written at least once lock-free, has no inferred guard)."""
        writes: Dict[str, List[AccessEvent]] = {}
        for e in self.snapshot():
            if e.kind == "write":
                writes.setdefault(e.name, []).append(e)
        out: Dict[str, Set[str]] = {}
        for attr, evs in writes.items():
            common = set(evs[0].held)
            for e in evs[1:]:
                common &= set(e.held)
            if common:
                out[attr] = common
        return out

    def guarded_violations(
        self, guards: Optional[Dict[str, Set[str]]] = None
    ) -> List[AccessEvent]:
        """Accesses that touched a guarded attribute without holding any
        of its guard locks. ``guards`` defaults to
        :meth:`inferred_guards` — pass the static JG007 guard map to
        corroborate a specific finding instead."""
        guards = self.inferred_guards() if guards is None else guards
        out = []
        for e in self.accesses():
            locks = guards.get(e.name)
            if locks and not (locks & set(e.held)):
                out.append(e)
        return out


def watch_attrs(
    obj: Any, attrs: Iterable[str], recorder: TraceRecorder
) -> Any:
    """Instrument ``obj`` so reads/writes of ``attrs`` are recorded with
    the accessing thread's held-lock set. Works by swapping in a
    dynamically-built subclass (zero new slots, so ``__slots__`` classes
    stay compatible); returns ``obj``."""
    watched = frozenset(attrs)
    cls = type(obj)

    def __getattribute__(self, name):  # noqa: N807
        if name in watched:
            recorder.record("read", name)
        return cls.__getattribute__(self, name)

    def __setattr__(self, name, value):  # noqa: N807
        if name in watched:
            recorder.record("write", name)
        cls.__setattr__(self, name, value)

    sub = type(
        f"Watched{cls.__name__}", (cls,),
        {
            "__slots__": (),
            "__getattribute__": __getattribute__,
            "__setattr__": __setattr__,
        },
    )
    obj.__class__ = sub
    return obj


# --------------------------------------------------------------------------
# seeded cooperative scheduler
# --------------------------------------------------------------------------


class DeadlockError(RuntimeError):
    """Every registered thread is blocked — the schedule wedged (e.g. a
    mutant deadlocked on a real, uninstrumented lock)."""


class CoopScheduler:
    """Seeded cooperative scheduler: registered threads run one at a
    time; at every yield point the seeded RNG picks who runs next.

    Usage::

        sched = CoopScheduler(seed=7)
        sched.spawn(writer_a)      # callables become managed threads
        sched.spawn(writer_b)
        sched.run()                # returns when every thread finished
                                   # (re-raises the first exception)

    Managed code calls ``sched.yield_point("tag")`` wherever an
    interleaving decision is interesting — between a check and an act,
    between two chunked writes. Unmanaged threads calling
    ``yield_point`` fall through instantly, so a yield point patched
    into library code is inert outside the harness.
    """

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)
        self._cond = threading.Condition()
        self._targets: List[Tuple[str, Callable[[], Any]]] = []
        self._threads: Dict[str, threading.Thread] = {}
        self._runnable: Set[str] = set()
        self._done: Set[str] = set()
        self._current: Optional[str] = None
        self._started = False
        self._errors: List[BaseException] = []
        self.schedule: List[str] = []      # decision log (for debugging)

    # -- setup ---------------------------------------------------------------

    def spawn(
        self, fn: Callable[[], Any], name: Optional[str] = None
    ) -> str:
        """Register ``fn`` as a managed thread (created at :meth:`run`).
        Returns the thread name."""
        if self._started:
            raise RuntimeError("spawn() after run()")
        name = name or f"coop-{len(self._targets)}"
        if any(n == name for n, _ in self._targets):
            raise ValueError(
                f"duplicate managed-thread name {name!r} — threads are "
                "keyed by name, a second spawn would silently replace "
                "the first"
            )
        self._targets.append((name, fn))
        return name

    def manages_current_thread(self) -> bool:
        """True iff the calling thread is one of this scheduler's
        managed threads (instrumented locks use this to decide between
        cooperative rescheduling and a real blocking acquire)."""
        return threading.current_thread().name in self._threads

    # -- managed-thread protocol --------------------------------------------

    def _trampoline(self, name: str, fn: Callable[[], Any]) -> None:
        try:
            self._wait_until_scheduled(name)
            fn()
        except BaseException as e:  # noqa: BLE001 — re-raised by run()
            with self._cond:
                self._errors.append(e)
        finally:
            with self._cond:
                self._done.add(name)
                self._runnable.discard(name)
                if self._current == name:
                    self._pick_next()
                self._cond.notify_all()

    def _wait_until_scheduled(self, name: str) -> None:
        with self._cond:
            while self._current != name:
                if name in self._done:
                    return
                self._cond.wait()

    def _pick_next(self) -> None:  # holds-lock: _cond
        """Choose the next runnable thread (or None); every caller
        already holds ``self._cond``."""
        candidates = sorted(self._runnable - self._done)
        if not candidates:
            self._current = None
            return
        self._current = self._rng.choice(candidates)
        self.schedule.append(self._current)

    def yield_point(self, tag: str = "") -> None:
        """A scheduling decision point. No-op on unmanaged threads."""
        if not self.manages_current_thread():
            return
        name = threading.current_thread().name
        with self._cond:
            self._runnable.add(name)
            self._pick_next()
            self._cond.notify_all()
            while self._current != name:
                self._cond.wait()

    # -- driver --------------------------------------------------------------

    def run(self, timeout: float = 30.0) -> List[str]:
        """Start every spawned thread, schedule until all finish.
        Returns the decision log; re-raises the first managed-thread
        exception; raises :class:`DeadlockError` on a wedged schedule."""
        self._started = True
        for name, fn in self._targets:
            t = threading.Thread(
                target=self._trampoline, args=(name, fn), name=name,
                daemon=True,
            )
            self._threads[name] = t
        with self._cond:
            self._runnable = {name for name, _ in self._targets}
            self._pick_next()
        for t in self._threads.values():
            t.start()
        # ONE deadline across all joins: a wedged schedule blocks every
        # managed thread, so per-thread timeouts would stack to
        # N x timeout before DeadlockError surfaces.
        deadline = time.monotonic() + timeout
        for t in self._threads.values():
            t.join(max(deadline - time.monotonic(), 0.0))
            if t.is_alive():
                with self._cond:
                    so_far = list(self.schedule)
                raise DeadlockError(
                    f"thread {t.name!r} still blocked after {timeout}s "
                    f"(schedule so far: {so_far})"
                )
        with self._cond:  # barrier: joins done, but be uniform anyway
            if self._errors:
                raise self._errors[0]
            return list(self.schedule)


# --------------------------------------------------------------------------
# instrumented locks
# --------------------------------------------------------------------------


class InstrumentedLock:
    """Drop-in ``threading.Lock`` replacement that records acquire /
    release into a :class:`TraceRecorder` and (optionally) cooperates
    with a :class:`CoopScheduler`: under a scheduler, acquisition is
    try-acquire-else-reschedule, so the one-thread-at-a-time discipline
    cannot deadlock on a lock the descheduled holder still owns."""

    def __init__(
        self, name: str = "lock", *,
        recorder: Optional[TraceRecorder] = None,
        scheduler: Optional[CoopScheduler] = None,
    ):
        self.name = name
        self._recorder = recorder
        self._scheduler = scheduler
        self._inner = threading.Lock()

    def _record(self, kind: str) -> None:
        if self._recorder is not None:
            self._recorder.record(kind, self.name)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        sched = self._scheduler
        if (
            sched is not None and blocking
            and sched.manages_current_thread()
        ):
            # Cooperative path (managed threads only — an unmanaged
            # thread would busy-spin here, yield_point being a no-op
            # for it). A timeout becomes a reschedule budget, so
            # acquire(timeout=...) can still return False.
            budget = (
                None if timeout is None or timeout < 0
                else max(int(timeout * 1000), 1)
            )
            while not self._inner.acquire(blocking=False):
                if budget is not None:
                    budget -= 1
                    if budget < 0:
                        return False
                sched.yield_point(f"blocked:{self.name}")
            self._record("acquire")
            return True
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._record("acquire")
        return ok

    def release(self) -> None:
        self._record("release")
        self._inner.release()
        if self._scheduler is not None:
            # A release is a natural preemption point: give waiters a
            # seeded chance to grab the lock before this thread re-runs.
            self._scheduler.yield_point(f"released:{self.name}")

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class InstrumentedCondition:
    """``threading.Condition`` wrapper with the same recording /
    cooperative-scheduling contract as :class:`InstrumentedLock`.
    ``wait`` under a scheduler is a bounded cooperative poll (release,
    reschedule, re-acquire, recheck) so a descheduled notifier can run.
    ``notify(n)`` grants wake exactly one waiter each (and persist if
    granted before the wait — the serialized scheduler would otherwise
    wedge on notify-then-wait orderings); ``notify_all`` wakes every
    current waiter via a generation bump."""

    def __init__(
        self, name: str = "cond", *,
        recorder: Optional[TraceRecorder] = None,
        scheduler: Optional[CoopScheduler] = None,
    ):
        self.name = name
        self._lock = InstrumentedLock(
            name, recorder=recorder, scheduler=scheduler
        )
        self._recorder = recorder
        self._scheduler = scheduler
        self._generation = 0   # bumped by notify_all: wakes every waiter
        self._wakeups = 0      # granted by notify(n): each wakes ONE

    def _record(self, kind: str) -> None:
        if self._recorder is not None:
            self._recorder.record(kind, self.name)

    def acquire(self, *args, **kwargs) -> bool:
        return self._lock.acquire(*args, **kwargs)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self) -> "InstrumentedCondition":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Caller must hold the condition (as with threading.Condition).
        Cooperative mode bounds an untimed wait at ~1000 reschedules —
        a test schedule that never notifies should fail fast as a
        deadlock, not hang CI. Real-thread mode polls at 1ms, so a
        timed wait's budget is timeout/1ms polls (≈ the requested wall
        time) and an untimed one is capped at ~60s — far beyond any
        sane test notify latency, but still bounded so a missed notify
        fails the test instead of hanging the suite."""
        self._record("wait")
        gen = self._generation
        if timeout is None:
            budget = 1000 if self._scheduler is not None else 60_000
        else:
            budget = max(int(timeout * 1000), 1)
        for _ in range(budget):
            self.release()
            if self._scheduler is not None:
                self._scheduler.yield_point(f"waiting:{self.name}")
            else:
                time.sleep(0.001)  # real threads: poll, don't spin
            self.acquire()
            if self._generation != gen:
                return True
            if self._wakeups > 0:   # claim ONE notify(n) grant
                self._wakeups -= 1
                return True
        return False

    def wait_for(
        self, predicate: Callable[[], bool],
        timeout: Optional[float] = None,
    ) -> bool:
        """threading.Condition.wait_for semantics: ONE overall deadline
        (a wake whose predicate is still false does NOT restart the
        clock), and an exhausted :meth:`wait` budget terminates an
        untimed wait_for too — the fail-fast bound wait() documents
        would otherwise be defeated by this loop re-entering it."""
        endtime = None
        waittime = timeout
        result = predicate()
        while not result:
            if waittime is not None:
                if endtime is None:
                    endtime = time.monotonic() + waittime
                else:
                    waittime = endtime - time.monotonic()
                    if waittime <= 0:
                        return predicate()
            if not self.wait(waittime):
                return predicate()
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        """Wake up to ``n`` waiters (threading.Condition semantics: a
        grant is consumed by ONE waiter, surplus grants persist for the
        next wait — which is also how a notify-before-wait behaves
        under the serialized scheduler)."""
        self._record("notify")
        self._wakeups += n

    def notify_all(self) -> None:
        self._record("notify")
        self._generation += 1
