"""Runtime sanitizers — promote silent performance/correctness rot into
hard, attributed errors. Opt-in (training pays nothing by default).

Three fences, composable via ``SanitizerConfig`` / ``JG_SANITIZE``:

* **recompile fence** — obs/recompile already *counts* XLA backend
  compiles; the fence marks a baseline once the step functions have
  warmed up and raises ``RecompileFenceError`` when post-warmup compiles
  exceed a budget. A shape-polymorphic step that silently retraces every
  batch is a minutes-per-step disaster on a remote-compile backend; in
  tests/CI it should fail loudly instead (OBSERVABILITY.md documents the
  budget convention).
* **transfer guard** — wraps the jitted step dispatch in
  ``jax.transfer_guard("disallow")`` so an implicit host->device
  transfer (a numpy batch leaking into the hot path, a closure constant
  being re-uploaded) raises instead of quietly serializing PCIe/ICI
  against the step.
* **NaN fence** — every ``nan_check_every`` steps, checks the step's
  loss/metrics (and optionally any pytree via ``check_finite``) for
  NaN/inf, emitting a structured ``sanitizer_trip`` obs event before
  raising ``NaNFenceError`` — the post-mortem trail shows *when* the
  loss went bad, not just that a later checkpoint was garbage.

Every trip increments the ``sanitizer_trips_total`` counter and (when a
telemetry sink is attached) emits a ``sanitizer_trip`` event before
raising, so a fenced CI failure is diagnosable from the event log alone.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
from typing import Any, Iterator, Mapping, Optional

TRIPS_TOTAL = "sanitizer_trips_total"

_ENV_ENABLE = "JG_SANITIZE"          # e.g. "recompile,transfer,nan"
_ENV_BUDGET = "JG_RECOMPILE_BUDGET"  # int, post-warmup compile budget
_ENV_NAN_EVERY = "JG_NAN_EVERY"      # int, NaN-fence stride


class SanitizerError(RuntimeError):
    """Base class for sanitizer trips."""


class RecompileFenceError(SanitizerError):
    pass


class NaNFenceError(SanitizerError):
    pass


@dataclasses.dataclass
class SanitizerConfig:
    recompile_fence: bool = False
    recompile_budget: int = 16  # post-warmup compiles allowed per run
    warmup_steps: int = 3       # compiles before this step are free
    transfer_guard: bool = False
    nan_fence: bool = False
    nan_check_every: int = 50

    @property
    def enabled(self) -> bool:
        return self.recompile_fence or self.transfer_guard or self.nan_fence

    @classmethod
    def from_spec(
        cls,
        spec: Optional[str],
        *,
        recompile_budget: Optional[int] = None,
        nan_check_every: Optional[int] = None,
    ) -> "SanitizerConfig":
        """Parse a comma list like ``"recompile,transfer,nan"`` (empty /
        None -> all fences off)."""
        cfg = cls()
        for item in (spec or "").split(","):
            item = item.strip().lower()
            if not item:
                continue
            if item in ("recompile", "recompiles", "recompile_fence"):
                cfg.recompile_fence = True
            elif item in ("transfer", "transfers", "transfer_guard"):
                cfg.transfer_guard = True
            elif item in ("nan", "nans", "nan_fence"):
                cfg.nan_fence = True
            else:
                raise ValueError(
                    f"unknown sanitizer {item!r} "
                    "(have: recompile, transfer, nan)"
                )
        if recompile_budget is not None:
            cfg.recompile_budget = int(recompile_budget)
        if nan_check_every is not None:
            cfg.nan_check_every = max(int(nan_check_every), 1)
        return cfg

    @classmethod
    def from_env(cls, env: Mapping[str, str] = os.environ) -> "SanitizerConfig":
        """The CI/tests activation path: ``JG_SANITIZE=recompile`` turns
        the fence on for every Trainer in the process without touching
        call sites."""
        return cls.from_spec(
            env.get(_ENV_ENABLE),
            recompile_budget=(
                int(env[_ENV_BUDGET]) if env.get(_ENV_BUDGET) else None
            ),
            nan_check_every=(
                int(env[_ENV_NAN_EVERY]) if env.get(_ENV_NAN_EVERY) else None
            ),
        )


class Sanitizer:
    """Per-run guard state. Thread one instance through a training run
    (the Trainer builds its own from ``TrainConfig.sanitize``, falling
    back to ``SanitizerConfig.from_env()``)."""

    def __init__(
        self,
        config: Optional[SanitizerConfig] = None,
        *,
        telemetry: Any = None,
        registry: Any = None,
    ):
        self.config = config or SanitizerConfig()
        self.telemetry = telemetry
        if registry is None:
            from ..obs import default_registry

            registry = default_registry()
        self._trips = registry.counter(
            TRIPS_TOTAL, "sanitizer fence trips (kind=recompile|nan)"
        )
        self._tracker = None
        self._baseline: Optional[int] = None
        self._steps = 0
        if self.config.recompile_fence:
            from ..obs import get_tracker

            self._tracker = get_tracker()

    @property
    def active(self) -> bool:
        return self.config.enabled

    # -- transfer guard -----------------------------------------------------

    @contextlib.contextmanager
    def guard_transfers(self) -> Iterator[None]:
        """``jax.transfer_guard_host_to_device("disallow")`` while
        enabled, else a no-op. Wrap ONLY the jitted dispatch with
        device-resident arguments — host reads of the results belong
        outside. Device-to-device stays allowed: GSPMD resharding (e.g.
        placing a fresh state onto the mesh on the first step) is a
        legitimate, one-off transfer; the footgun this fence exists for
        is host batches/constants leaking into the hot path."""
        if not self.config.transfer_guard:
            yield
            return
        import jax

        with jax.transfer_guard_host_to_device("disallow"):
            yield

    # -- step-driven fences (recompile + NaN) --------------------------------

    def pin_baseline(self, count: int) -> None:
        """Pin the recompile-fence baseline to an explicit tracker
        count instead of letting ``after_step`` mark it after
        ``warmup_steps``. This is how an AOT boot-from-store (aot/,
        PERF.md "Cold start") tightens the fence from budget-N-post-
        warmup to budget-ZERO-post-BOOT: the server marks the tracker
        at the very start of boot and pins it here once the store hit
        confirms nothing should compile from that point on. The
        classifier server also pins a large sentinel around a hot
        reload's legitimate off-path compile and re-pins to the real
        count afterwards."""
        self._baseline = int(count)

    def after_step(
        self,
        step: Optional[int] = None,
        metrics: Any = None,
        *,
        n_steps: int = 1,
    ) -> None:
        """Feed one finished dispatch covering ``n_steps`` optimizer
        steps (a scan chunk / whole-epoch program advances by its chunk
        size). ``step`` defaults to an internal counter; ``metrics`` is
        the step's metrics dict (device scalars are fine — they are only
        synced on NaN-check strides)."""
        n_steps = max(int(n_steps), 1)
        self._steps += n_steps
        step = self._steps if step is None else int(step)
        cfg = self.config
        if cfg.recompile_fence and self._tracker is not None:
            if self._baseline is None:
                if step >= cfg.warmup_steps:
                    self._baseline = self._tracker.count
            else:
                excess = self._tracker.count - self._baseline
                if excess > cfg.recompile_budget:
                    self._trip(
                        "recompile",
                        RecompileFenceError(
                            f"{excess} backend compiles after warmup "
                            f"(step {step}) exceed the budget of "
                            f"{cfg.recompile_budget} — a shape/static-arg "
                            "leak is retracing the hot path (see obs/"
                            "recompile + OBSERVABILITY.md)"
                        ),
                        step=step,
                        excess=excess,
                        budget=cfg.recompile_budget,
                    )
        # Stride test is "did this dispatch cross a check boundary" (the
        # trainer's log-interval idiom), not exact divisibility — a scan
        # chunk advancing by S would otherwise only check on multiples
        # of lcm(S, stride), i.e. possibly never.
        if (
            cfg.nan_fence
            and metrics is not None
            and step % max(cfg.nan_check_every, 1) < n_steps
        ):
            self.check_finite(metrics, step=step)

    def check_finite(self, tree: Any, *, step: Optional[int] = None) -> None:
        """Raise ``NaNFenceError`` if any float leaf of ``tree`` holds a
        NaN/inf. Forces a host sync — that is the point; call it on the
        fence stride, not every step."""
        if not self.config.nan_fence:
            return
        import jax
        import jax.numpy as jnp

        bad = []
        for path, leaf in _named_leaves(tree):
            try:
                arr = jnp.asarray(leaf)
            except (TypeError, ValueError):
                continue
            if not jnp.issubdtype(arr.dtype, jnp.inexact):
                continue
            if not bool(jax.device_get(jnp.all(jnp.isfinite(arr)))):
                bad.append(path or "<value>")
        if bad:
            self._trip(
                "nan",
                NaNFenceError(
                    f"non-finite value(s) at step {step}: "
                    f"{', '.join(bad[:8])}"
                    + (" …" if len(bad) > 8 else "")
                    + " — loss/grads went NaN/inf (check LR, loss scale, "
                    "binarization clamp)"
                ),
                step=step,
                leaves=bad[:8],
            )

    # -- shared trip path ----------------------------------------------------

    def _trip(self, kind: str, error: SanitizerError, **fields: Any) -> None:
        self._trips.inc(kind=kind)
        if self.telemetry is not None:
            try:
                self.telemetry.emit(
                    "sanitizer_trip", fence=kind,
                    error=str(error)[:500], **fields,
                )
            except (AttributeError, OSError, TypeError, ValueError):
                pass  # the trip error itself must still propagate
        raise error


def _named_leaves(tree: Any, prefix: str = "") -> Iterator[tuple]:
    """(dotted-path, leaf) pairs without requiring jax tree utils on
    plain dict/list metrics."""
    if isinstance(tree, Mapping):
        for k, v in tree.items():
            yield from _named_leaves(v, f"{prefix}.{k}" if prefix else str(k))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _named_leaves(v, f"{prefix}[{i}]")
    else:
        yield prefix, tree
