"""Command-line entry point.

Covers the reference's argparse surface (identical flag set across its
scripts: -n/--nodes, -g/--gpus, -nr, --epochs, --lr, --seed,
--log-interval; mnist-dist2.py:23-38) plus everything the reference
hardcodes (batch size, backend, master address, normalization), as flags.

Usage examples:
  python -m distributed_mnist_bnns_tpu.cli train --model bnn-mlp-large \
      --epochs 5 --batch-size 64 --lr 0.01
  python -m distributed_mnist_bnns_tpu.cli train --model convnet --dp auto
  python -m distributed_mnist_bnns_tpu.cli eval --checkpoint-dir ckpts
  # multi-host (one process per host; replaces env:// rendezvous):
  python -m distributed_mnist_bnns_tpu.cli train --nodes 2 --node-rank 0 \
      --coordinator 10.0.0.1:8888
"""

from __future__ import annotations

import argparse
import logging
import sys

log = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="distributed_mnist_bnns_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp):
        sp.add_argument("--model", default="bnn-mlp-large")
        sp.add_argument("--infl-ratio", type=int, default=3,
                        help="width multiplier for the BNN MLPs")
        sp.add_argument("--epochs", type=int, default=5)
        sp.add_argument("--batch-size", type=int, default=64)
        sp.add_argument("--optimizer", default="adam")
        sp.add_argument("--lr", type=float, default=0.01)
        sp.add_argument("--lr-schedule", default="step",
                        choices=["step", "cosine"],
                        help="step = reference x0.1-every-40-epochs decay; "
                             "cosine anneals to 0 over --epochs")
        sp.add_argument("--warmup-epochs", type=int, default=0)
        sp.add_argument("--clip-grad-norm", type=float, default=None,
                        help="global-norm gradient clipping threshold")
        sp.add_argument("--seed", type=int, default=42)
        sp.add_argument("--log-interval", type=int, default=100)
        from .ops.xnor_gemm import BACKENDS

        sp.add_argument("--backend", default=None,
                        choices=[None, *BACKENDS])
        sp.add_argument("--stochastic", action="store_true",
                        help="stochastic activation binarization "
                             "(reference quant_mode='stoch')")
        sp.add_argument("--xnor-scale", action="store_true",
                        help="XNOR-Net per-channel alpha rescaling on "
                             "binarized GEMMs (models that support it)")
        sp.add_argument("--dropout", type=float, default=None,
                        help="dropout rate for the transformer families "
                             "(bnn-vit*; the MLP topologies carry their "
                             "reference-fixed rates); composes with --pp")
        sp.add_argument("--profile-dir", default=None,
                        help="write a jax.profiler trace of the first "
                             "trained epoch's early steps here")
        sp.add_argument("--profile-steps", default=None, metavar="A:B",
                        help="step-windowed device capture "
                             "(OBSERVABILITY.md 'Device profiling'): "
                             "start the jax.profiler trace at "
                             "cumulative optimizer step A, stop at B, "
                             "into --profile-dir (or <telemetry-dir>/"
                             "profile); summarize with `cli profile`. "
                             "Supersedes the first-epoch --profile-dir "
                             "heuristic")
        sp.add_argument("--telemetry-dir", default=None,
                        help="write structured run telemetry here: JSONL "
                             "events (manifest/step/epoch/checkpoint), "
                             "per-process heartbeats, recompile counts "
                             "(OBSERVABILITY.md); read back with the "
                             "`telemetry` subcommand")
        sp.add_argument("--trace", action=argparse.BooleanOptionalAction,
                        default=None,
                        help="span-tree tracing into the telemetry event "
                             "log (OBSERVABILITY.md 'Tracing'): step/"
                             "checkpoint/restore/remesh windows become "
                             "`cli trace`-readable spans. Default: the "
                             "JG_TRACE env var; needs --telemetry-dir")
        sp.add_argument("--sanitize", default=None, metavar="FENCES",
                        help="arm runtime fences (ANALYSIS.md): comma "
                             "list of 'recompile' (hard-error when "
                             "post-warmup XLA compiles exceed the "
                             "budget), 'transfer' (disallow implicit "
                             "host<->device transfers around the jitted "
                             "step), 'nan' (loss NaN/inf fence). "
                             "Default: the JG_SANITIZE env var")
        sp.add_argument("--recompile-budget", type=int, default=None,
                        help="post-warmup compile budget for "
                             "--sanitize recompile (default 16)")
        sp.add_argument("--nan-check-every", type=int, default=None,
                        help="NaN-fence stride in steps for "
                             "--sanitize nan (each check syncs; "
                             "default 50)")
        sp.add_argument("--chaos", default=None, metavar="SPEC",
                        help="fault-injection spec (RESILIENCE.md): "
                             "';'-separated kind@k=v entries, e.g. "
                             "'step_fault@step=5;ckpt_corrupt@epoch=1;"
                             "preempt@step=12'. Kinds: step_fault, "
                             "data_io, preempt, slow_host, ckpt_corrupt, "
                             "ckpt_truncate, infer_slow, infer_error, "
                             "worker_lost, worker_restore (the last two "
                             "need --elastic). "
                             "Default: the JG_CHAOS env var")
        sp.add_argument("--elastic", action="store_true",
                        help="elastic data-parallel membership "
                             "(RESILIENCE.md 'Elastic membership'): a "
                             "chaos worker_lost/worker_restore shrinks/"
                             "regrows the mesh in-process, re-placing "
                             "state from the newest digest-verified "
                             "checkpoint generation instead of "
                             "restarting the job. Needs "
                             "--checkpoint-dir; DP only (TP/PP/"
                             "device-data/orbax rejected)")
        sp.add_argument("--checkpoint-keep", type=int, default=3,
                        help="checkpoint generations kept for corruption "
                             "rollback (digest-verified on resume)")
        sp.add_argument("--no-preemption", action="store_true",
                        help="do NOT turn SIGTERM/SIGINT into a graceful "
                             "stop + mid-epoch checkpoint + exit 75 "
                             "(resumable); default is preemption-aware")
        sp.add_argument("--loss", default="ce",
                        choices=["ce", "hinge", "sqrt_hinge"])
        sp.add_argument("--label-smoothing", type=float, default=0.0,
                        help="uniform target mixing for the ce loss")
        sp.add_argument("--augment", action="store_true",
                        help="device-side random crop+flip inside the "
                             "train step (the CIFAR recipe)")
        sp.add_argument("--precision", default="fp32",
                        choices=["fp32", "bf16"],
                        help="bf16 = mixed precision (AMP O2 parity)")
        sp.add_argument("--scan-steps", type=int, default=1,
                        help="fuse N train steps into one lax.scan dispatch "
                             "(device-resident inner loop; single-device, "
                             "--dp-mode gspmd incl. multi-host, "
                             "single-process fsdp, and either DP mode "
                             "combined with --grad-compress)")
        sp.add_argument("--device-data", action="store_true",
                        help="keep the whole dataset on device and run "
                             "each epoch as ONE dispatch (dataset must "
                             "fit HBM; single-process, gspmd)")
        sp.add_argument("--grad-accum", type=int, default=1,
                        help="microbatches per optimizer step (activation-"
                             "memory saver; batch-size must divide evenly)")
        sp.add_argument("--remat", action="store_true",
                        help="rematerialize activations in backward "
                             "(jax.checkpoint) to cut HBM use")
        sp.add_argument("--dataset", default="mnist",
                        choices=["mnist", "cifar10", "imagenet"])
        sp.add_argument("--data-dir", default=None)
        sp.add_argument("--norm", default=None,
                        choices=["mnist", "cifar", "imagenet", "half",
                                 "none"],
                        help="default: the dataset's own statistics")
        sp.add_argument("--image-size", type=int, default=224,
                        help="imagenet decode/synthetic resolution")
        sp.add_argument("--synthetic-sizes", type=int, nargs=2,
                        default=None, metavar=("TRAIN", "TEST"),
                        help="fallback synthetic dataset sizes")
        sp.add_argument("--checkpoint-dir", default=None)
        sp.add_argument("--save-all", action="store_true")
        sp.add_argument("--async-checkpoint", action="store_true",
                        help="overlap checkpoint serialization/IO with "
                             "training (background writer thread)")
        sp.add_argument("--checkpoint-backend", default="msgpack",
                        choices=["msgpack", "orbax"],
                        help="orbax = sharded per-process writes, "
                             "restores onto the live shardings (pod "
                             "scale); msgpack = single-file rank-0 "
                             "writer (default)")
        sp.add_argument("--native-loader", action="store_true",
                        help="gather batches on C++ worker threads "
                             "(native BatchPool; python fallback if the "
                             "toolchain is unavailable)")
        sp.add_argument("--resume", action="store_true")
        sp.add_argument("--results", default=None)
        sp.add_argument("--timing-csv", default=None,
                        help="prefix for per-batch/per-epoch timing CSVs")
        # parallelism
        sp.add_argument("--dp", default="1",
                        help="'auto' = all devices, or an integer")
        sp.add_argument("--dp-mode", default="gspmd",
                        choices=["gspmd", "fsdp"],
                        help="fsdp = ZeRO-style sharded params/opt state")
        sp.add_argument("--grad-compress", default="none",
                        choices=["none", "sign", "sign_ef"],
                        help="1-bit gradient exchange (PERF.md "
                             "'Gradient comms'): sign bitplanes + per-"
                             "bucket fp32 scales, ~32x fewer wire bytes "
                             "per step; sign = majority-vote signSGD, "
                             "sign_ef = error feedback (residuals "
                             "checkpoint in the optimizer state). "
                             "Composes with --dp-mode fsdp (compressed "
                             "reduce-scatter + 1-bit update all-gather "
                             "over ZeRO-sharded optimizer state) and "
                             "with --scan-steps; TP/PP/device-data "
                             "rejected")
        sp.add_argument("--dp-hosts", type=int, default=None,
                        help="two-level hierarchical compressed "
                             "exchange: factor the DP world into "
                             "(hosts x local); fp32 ring reduce within "
                             "a host's 'local' mesh axis, 1-bit "
                             "exchange across the inter-host axis only "
                             "(needs --grad-compress, --dp-mode gspmd)")
        sp.add_argument("--compress-bucket-size", type=int, default=1024,
                        help="elements per compression scale bucket "
                             "(multiple of 32)")
        sp.add_argument("--compress-chunks", type=int, default=4,
                        help="independent overlap groups for the "
                             "compressed exchange (comm of group i "
                             "overlaps packing of group i+1)")
        sp.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel width: Megatron col/row "
                             "sharding over a 'model' mesh axis (MLP/QNN "
                             "and ViT families); builds a (dp x tp) mesh")
        sp.add_argument("--pp", type=int, default=1,
                        help="pipeline-parallel stages: GPipe the "
                             "transformer block stack over N devices "
                             "(bnn-vit models; depth %% N == 0)")
        sp.add_argument("--pp-microbatches", type=int, default=0,
                        help="microbatches per pipelined step "
                             "(0 = one per stage)")
        sp.add_argument("--pp-remat", action="store_true",
                        help="checkpoint each pipeline stage: activation "
                             "memory bounded per microbatch (1F1B-class) "
                             "at the cost of recompute in backward")
        sp.add_argument("--log-file", default="log.txt")
        sp.add_argument("--aot", action="store_true",
                        help="consult the AOT executable store for the "
                             "jitted train step (aot/, PERF.md 'Cold "
                             "start'): hit = first step pays no trace/"
                             "compile; miss = compile once and bank. "
                             "Also enabled by JG_AOT=1")
        sp.add_argument("--aot-dir", default=None,
                        help="AOT store root (default: JG_AOT_STORE or "
                             "<repo>/.jax_aot)")
        # multi-host rendezvous (replaces MASTER_ADDR/MASTER_PORT env://)
        sp.add_argument("--nodes", type=int, default=1)
        sp.add_argument("--node-rank", type=int, default=0)
        sp.add_argument("--coordinator", default=None,
                        help="host:port of process 0")
        sp.add_argument("--init-timeout", type=float, default=60.0,
                        help="per-attempt coordinator handshake deadline "
                             "(seconds) for jax.distributed.initialize")
        sp.add_argument("--init-retries", type=int, default=3,
                        help="retry budget for retryable bootstrap "
                             "failures (coordinator-unreachable/timeout; "
                             "rank collisions fail fast)")

    t = sub.add_parser("train", help="train a model")
    common(t)
    t.add_argument("--stream", action="store_true",
                   help="imagenet only: train on the streaming pipeline "
                        "(decode per batch, whole dataset, never "
                        "materialized; Trainer.fit_stream) with the "
                        "materialized val subset for eval")
    e = sub.add_parser("eval", help="evaluate latest/best checkpoint")
    common(e)
    e.add_argument("--best", action="store_true")
    x = sub.add_parser(
        "export",
        help="freeze a trained checkpoint (bnn-mlp, bnn-cnn, xnor-resnet, "
             "bnn-vit, bnn-moe-mlp or qnn-mlp) into the packed/int8 "
             "serving artifact (infer.load_packed)",
    )
    common(x)
    x.add_argument("--best", action="store_true")
    x.add_argument("--out", default="model_packed.msgpack")
    sv = sub.add_parser(
        "serve",
        help="long-running resilient HTTP inference server over a "
             "packed artifact (from `export`): bounded admission queue "
             "with load shedding, per-request deadlines, dynamic "
             "micro-batching at the compiled batch shape, circuit "
             "breaker on backend failures/stalls, hot artifact reload, "
             "SIGTERM graceful drain (SERVING.md)",
    )
    sv.add_argument("--artifact", required=True,
                    help="path to an export-ed packed .msgpack artifact")
    sv.add_argument("--lm", action="store_true",
                    help="serve a packed causal-LM artifact (from "
                         "`lm --export`) through the continuous-batching "
                         "generation engine instead of the classifier "
                         "micro-batcher: paged KV cache, iteration-level "
                         "scheduling, streaming POST /generate "
                         "(SERVING.md 'Continuous LM serving')")
    sv.add_argument("--slots", type=int, default=4,
                    help="--lm: decode batch width — the ONE compiled "
                         "decode signature; streams join/leave slots at "
                         "any iteration")
    sv.add_argument("--page-size", type=int, default=16,
                    help="--lm: tokens per KV page")
    sv.add_argument("--num-pages", type=int, default=None,
                    help="--lm: KV pool pages (default: every slot can "
                         "reach max_len simultaneously, + the null page)")
    sv.add_argument("--prefill-chunk", type=int, default=16,
                    help="--lm: prompt positions per prefill dispatch")
    sv.add_argument("--max-len", type=int, default=None,
                    help="--lm: cap sequences below the artifact's "
                         "trained window (smaller pages/pools)")
    sv.add_argument("--max-new-tokens", type=int, default=64,
                    help="--lm: default generation length when the "
                         "request doesn't set max_new_tokens")
    sv.add_argument("--max-prompt-tokens", type=int, default=None,
                    help="--lm: reject longer prompts with 413 "
                         "(default: max_len - 1)")
    sv.add_argument("--prefix-cache", action="store_true",
                    help="--lm: copy-on-write prompt-prefix sharing "
                         "over the paged KV pool — requests sharing a "
                         "prompt prefix (system prompts) prefill it "
                         "once; full pages fork refcounted into new "
                         "sequences and publish back to a radix index "
                         "at eviction, LRU-evicted under pool pressure "
                         "(SERVING.md 'Prefix caching')")
    sv.add_argument("--spec-decode", type=int, default=0, metavar="K",
                    help="--lm: self-speculative decoding — per round, "
                         "K-1 tokens drafted through the packed 1-bit "
                         "decode program and the whole K-token window "
                         "verified in ONE dense-bf16 dispatch; greedy "
                         "output is token-identical to the verifier "
                         "alone, accept/reject is host-side so the "
                         "compiled signatures stay fixed (SERVING.md "
                         "'Speculative decoding'). 0 = off")
    sv.add_argument("--kernels", action="store_true",
                    help="--lm: arm the Pallas serving path — paged "
                         "attention walks the page table in-kernel (no "
                         "materialized K/V gather) and packed "
                         "projections run the fused unpack-GEMM "
                         "(weights cross HBM at 1/32 byte/param). Same "
                         "three-program set, token-identical greedy "
                         "output; off = the gather/popcount oracle path")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=8000,
                    help="0 = pick an ephemeral port (logged)")
    sv.add_argument("--batch-size", type=int, default=32,
                    help="compiled micro-batch shape; queued requests "
                         "coalesce up to it, the remainder is padded — "
                         "one compile serves the whole run")
    sv.add_argument("--queue-depth", type=int, default=64,
                    help="admission bound: requests past it are shed "
                         "with an immediate 503 (reject-new over "
                         "collapse)")
    sv.add_argument("--deadline-ms", type=float, default=None,
                    help="default per-request deadline (clients may "
                         "send their own deadline_ms); queued work "
                         "past its deadline is cancelled, never "
                         "computed. Default: 1000 for the classifier "
                         "server, 30000 for --lm (a stream spans many "
                         "decode iterations)")
    sv.add_argument("--linger-ms", type=float, default=2.0,
                    help="micro-batch coalescing window")
    sv.add_argument("--stall-timeout-s", type=float, default=1.0,
                    help="a predictor call slower than this counts as "
                         "a breaker failure even if it returns")
    sv.add_argument("--breaker-threshold", type=int, default=3,
                    help="consecutive backend failures/stalls that "
                         "trip the circuit breaker open")
    sv.add_argument("--breaker-reset-s", type=float, default=5.0,
                    help="open -> half-open reset timeout")
    sv.add_argument("--breaker-probes", type=int, default=1,
                    help="half-open probe batches before closing")
    sv.add_argument("--drain-timeout-s", type=float, default=30.0,
                    help="SIGTERM flush budget for in-flight requests")
    sv.add_argument("--input-shape", type=int, nargs="+",
                    default=[28, 28, 1],
                    help="per-example input shape for the warmup "
                         "compile (match the artifact's family)")
    sv.add_argument("--telemetry-dir", default=None,
                    help="JSONL request/shed/breaker/drain events here "
                         "(OBSERVABILITY.md)")
    sv.add_argument("--trace", action=argparse.BooleanOptionalAction,
                    default=None,
                    help="per-request span trees into the event log "
                         "(OBSERVABILITY.md 'Tracing'): admit/queue/"
                         "dispatch/respond (and the LM engine's "
                         "prefill/decode-iteration) phases, joined "
                         "across processes by the x-jg-trace header; "
                         "read back with `cli trace`. Default: the "
                         "JG_TRACE env var; needs --telemetry-dir")
    sv.add_argument("--costs", action=argparse.BooleanOptionalAction,
                    default=None,
                    help="per-program HLO cost ledger + measured MFU "
                         "(OBSERVABILITY.md 'Device profiling'): "
                         "cost_analysis/memory_analysis at every "
                         "compile, per-program MFU in /healthz. "
                         "Default: the JG_COSTS env var")
    sv.add_argument("--events-max-bytes", type=int, default=None,
                    help="size-rotate the events.jsonl past this many "
                         "bytes (long-lived servers; readers span the "
                         "rotated segments). Default: the "
                         "JG_EVENTS_MAX_BYTES env var, else unbounded")
    sv.add_argument("--chaos", default=None, metavar="SPEC",
                    help="serving fault injection (RESILIENCE.md): "
                         "e.g. 'infer_error@step=4,times=3;"
                         "infer_slow@p=0.1,delay_s=0.5'. Default: the "
                         "JG_CHAOS env var")
    sv.add_argument("--seed", type=int, default=0)
    sv.add_argument("--interpret", action=argparse.BooleanOptionalAction,
                    default=None,
                    help="run the packed kernels in interpreter mode "
                         "(default: auto - real Mosaic on TPU, "
                         "interpreter elsewhere)")
    sv.add_argument("--aot", action="store_true",
                    help="boot from the AOT executable store (aot/, "
                         "PERF.md 'Cold start'): a warm store serves "
                         "the first request with ZERO XLA compiles and "
                         "arms the recompile fence at budget 0 from "
                         "boot; a miss compiles as usual and re-banks. "
                         "Build the store with `cli aot build`")
    sv.add_argument("--aot-dir", default=None,
                    help="AOT store root (default: JG_AOT_STORE or "
                         "<repo>/.jax_aot)")
    sv.add_argument("--log-file", default="log.txt")
    fl = sub.add_parser(
        "fleet",
        help="multi-replica serving fleet (SERVING.md 'Fleet'): a "
             "deadline-aware least-loaded router over N `cli serve` "
             "replica subprocesses with per-replica health probing + "
             "circuit breakers, retry-on-another-replica failover, "
             "autoscaling between --min/--max replicas off sustained "
             "queue depth + shed rate, rolling artifact deploys with "
             "canary gates and automatic fleet-wide rollback "
             "(POST /admin/rollout), SIGTERM whole-fleet drain. "
             "`fleet explain DIR` replays a fleet telemetry dir's "
             "control-plane decision timeline instead of serving",
    )
    fl.add_argument("action", nargs="*", default=[],
                    metavar="explain DIR",
                    help="'explain DIR': render the control-plane "
                         "decision audit timeline (autoscaler scale/"
                         "holds, breaker transitions, ejections, "
                         "respawns, rollout gates) joined against SLO "
                         "alert open/close from DIR's event log "
                         "(OBSERVABILITY.md 'Fleet observability'); "
                         "with no action, run the fleet server")
    fl.add_argument("--artifact", default=None,
                    help="packed artifact every replica serves (from "
                         "`export` / `lm --export`); required unless "
                         "running `fleet explain`")
    fl.add_argument("--lm", action="store_true",
                    help="LM fleet: `cli serve --lm` replicas routed "
                         "via POST /generate with prefix-affinity "
                         "(requests sharing the first page-size prompt "
                         "block land on the replica whose prefix cache "
                         "is warm)")
    fl.add_argument("--host", default="127.0.0.1")
    fl.add_argument("--port", type=int, default=8100,
                    help="router port (0 = ephemeral, logged)")
    fl.add_argument("--replicas", type=int, default=2,
                    help="initial replica count")
    fl.add_argument("--min-replicas", type=int, default=1)
    fl.add_argument("--max-replicas", type=int, default=4)
    fl.add_argument("--deadline-ms", type=float, default=1000.0,
                    help="default client deadline at the router; an "
                         "expired deadline fails fast with NO dispatch")
    fl.add_argument("--max-attempts", type=int, default=3,
                    help="dispatch attempts per request (failover to "
                         "another replica on error/shed)")
    fl.add_argument("--probe-interval-s", type=float, default=0.25,
                    help="replica /healthz poll cadence (ejection on "
                         "failed/draining/fence_error)")
    fl.add_argument("--breaker-threshold", type=int, default=3,
                    help="per-replica router breaker: consecutive "
                         "failures to eject")
    fl.add_argument("--breaker-reset-s", type=float, default=1.0)
    fl.add_argument("--boot-timeout-s", type=float, default=180.0,
                    help="replica spawn -> healthy budget before the "
                         "supervisor kills and respawns it")
    fl.add_argument("--autoscale", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="scale replicas between --min/--max off "
                         "sustained mean queue depth + shed rate "
                         "(cheap because --aot replicas cold-start in "
                         "~1.7s with zero compiles)")
    fl.add_argument("--queue-high", type=float, default=4.0,
                    help="mean replica queue depth that (sustained) "
                         "scales up")
    fl.add_argument("--queue-low", type=float, default=0.5,
                    help="mean queue depth below which (sustained, "
                         "zero sheds) the fleet scales down")
    fl.add_argument("--sustain-s", type=float, default=1.0,
                    help="how long an autoscale signal must hold")
    fl.add_argument("--cooldown-s", type=float, default=3.0,
                    help="minimum gap between autoscale decisions")
    fl.add_argument("--scrape-interval-s", type=float, default=1.0,
                    help="replica /metrics scrape cadence feeding the "
                         "fleet-merged GET /metrics (counters sum, "
                         "gauges fan out per replica, histograms merge "
                         "le-exactly; OBSERVABILITY.md 'Fleet "
                         "observability'); 0 disables scraping")
    fl.add_argument("--slo", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="multiwindow burn-rate SLO alerting over "
                         "routed availability + request p99 (+ LM "
                         "inter-token p99): slo_alert events, "
                         "slo_burn_rate/slo_budget_remaining gauges, "
                         "open alerts in /healthz")
    fl.add_argument("--slo-fast-window-s", type=float, default=60.0,
                    help="SLO fast burn window (alerts open when fast "
                         "AND slow burns exceed thresholds, close when "
                         "the fast window drains)")
    fl.add_argument("--slo-slow-window-s", type=float, default=300.0,
                    help="SLO slow burn window")
    fl.add_argument("--drain-timeout-s", type=float, default=60.0,
                    help="SIGTERM whole-fleet drain budget")
    fl.add_argument("--staging-dir", default=None,
                    help="rollout artifact staging dir (artifacts ship "
                         "here over utils/transfer, digest-verified; "
                         "default: <telemetry-dir>/staging)")
    fl.add_argument("--input-shape", type=int, nargs="+",
                    default=[28, 28, 1],
                    help="per-example input shape (builds the rollout "
                         "canary probe request)")
    fl.add_argument("--page-size", type=int, default=16,
                    help="--lm: tokens per KV page — also the "
                         "prefix-affinity block size (must match the "
                         "replicas')")
    fl.add_argument("--telemetry-dir", default=None,
                    help="fleet events here; each replica logs under "
                         "<dir>/replica-N/ (ids are nonce-prefixed so "
                         "the logs merge)")
    fl.add_argument("--trace", action=argparse.BooleanOptionalAction,
                    default=None,
                    help="router span trees (fleet.request/dispatch) "
                         "into the event log; the x-jg-trace header is "
                         "forwarded unchanged so replica spans join "
                         "the same trace. Default: the JG_TRACE env "
                         "var; needs --telemetry-dir")
    fl.add_argument("--events-max-bytes", type=int, default=None)
    fl.add_argument("--seed", type=int, default=0)
    fl.add_argument("--batch-size", type=int, default=None,
                    help="replica micro-batch size (passed through)")
    fl.add_argument("--queue-depth", type=int, default=None,
                    help="replica admission bound (passed through)")
    fl.add_argument("--stall-timeout-s", type=float, default=None,
                    help="replica stall budget (passed through)")
    fl.add_argument("--chaos", default=None, metavar="SPEC",
                    help="replica fault injection (passed through to "
                         "every replica; RESILIENCE.md)")
    fl.add_argument("--interpret", action=argparse.BooleanOptionalAction,
                    default=None,
                    help="replica interpreter mode (passed through)")
    fl.add_argument("--aot", action="store_true",
                    help="replicas boot from the AOT executable store "
                         "(zero-compile cold starts make respawn + "
                         "autoscale cheap; build with `cli aot build`)")
    fl.add_argument("--aot-dir", default=None)
    fl.add_argument("--replica-arg", action="append", default=None,
                    metavar="ARG",
                    help="extra raw `cli serve` argv token passed to "
                         "every replica; repeatable (e.g. "
                         "--replica-arg=--slots --replica-arg=8)")
    fl.add_argument("--json", action="store_true",
                    help="`fleet explain`: emit the decision timeline "
                         "as JSON rows instead of the table")
    fl.add_argument("--log-file", default="log.txt")
    inf = sub.add_parser(
        "infer",
        help="serve a packed 1-bit artifact (from `export`): evaluate "
             "it on the dataset's test split and report accuracy + "
             "per-batch latency (one-shot; see `serve` for the "
             "long-running server)",
    )
    common(inf)
    inf.add_argument("--artifact", required=True,
                     help="path to an export-ed packed .msgpack artifact")
    inf.add_argument("--interpret", action=argparse.BooleanOptionalAction,
                     default=None,
                     help="run the packed kernels in interpreter mode "
                          "(default: auto - real Mosaic on TPU, "
                          "interpreter elsewhere)")
    lm = sub.add_parser(
        "lm",
        help="train the causal binarized LM (byte-level on --corpus, "
             "else a synthetic corpus); --ring for sequence-parallel "
             "attention, --pp for the model-level pipeline",
    )
    lm.add_argument("--steps", type=int, default=200)
    lm.add_argument("--seq-len", type=int, default=32)
    lm.add_argument("--batch-size", type=int, default=16)
    lm.add_argument("--depth", type=int, default=2)
    lm.add_argument("--embed-dim", type=int, default=128)
    lm.add_argument("--num-heads", type=int, default=4)
    lm.add_argument("--lr", type=float, default=3e-3)
    lm.add_argument("--seed", type=int, default=0)
    lm.add_argument("--attention", default="xla", choices=["xla", "flash"])
    lm.add_argument("--ring", action="store_true")
    lm.add_argument("--corpus", default=None)
    lm.add_argument("--pp", type=int, default=1)
    lm.add_argument("--sample", type=int, default=0,
                    help="generate N tokens after training")
    lm.add_argument("--temperature", type=float, default=0.8)
    lm.add_argument("--export", default=None, metavar="PATH",
                    help="freeze the trained LM to a packed 1-bit "
                         "serving artifact (KV-cache decoding: "
                         "infer_transformer.make_lm_decoder)")
    lm.add_argument("--load", default=None, metavar="PATH",
                    help="skip training: load a packed artifact (from "
                         "--export) and generate --sample tokens via "
                         "the KV-cache decoder")
    lm.add_argument("--prompt", default=None,
                    help="with --load: text prompt (byte tokens; "
                         "default a newline)")
    lm.add_argument("--interpret", action=argparse.BooleanOptionalAction,
                    default=None,
                    help="with --load: force the Pallas interpret path "
                         "(default: interpret off-TPU, kernels on)")
    lm.add_argument("--log-interval", type=int, default=25)
    lm.add_argument("--log-file", default="log.txt")
    tm = sub.add_parser(
        "telemetry",
        help="summarize a run's telemetry event log (from "
             "--telemetry-dir or bench --events) into a human-readable "
             "table; --json for tooling",
    )
    tm.add_argument("log",
                    help="path to an events.jsonl, or the telemetry "
                         "directory containing one")
    tm.add_argument("--fleet", action="store_true",
                    help="treat LOG as a fleet telemetry directory: "
                         "summarize the router log plus every "
                         "replica's subdirectory log into one combined "
                         "report (rotated segments spanned per log)")
    tm.add_argument("--json", action="store_true",
                    help="emit the summary as one JSON object instead "
                         "of a table")
    tc = sub.add_parser(
        "trace",
        help="read a traced run's span trees (OBSERVABILITY.md "
             "'Tracing'): render the p99 tail-attribution report "
             "(where did the slow requests' time go — queue vs prefill "
             "vs decode vs stall), and/or export Chrome-trace-event "
             "JSON loadable in Perfetto / chrome://tracing. Multiple "
             "logs (router dir + replica dirs) are STITCHED across the "
             "x-jg-trace hop: replica request trees re-parent under "
             "their router dispatch spans, so tail attribution splits "
             "router queueing from replica queue/infer time",
    )
    tc.add_argument("log", nargs="+",
                    help="path(s) to events.jsonl files or telemetry "
                         "directories; pass the router dir plus its "
                         "replica dirs to join span trees across "
                         "processes (dir basenames must be the replica "
                         "ids, as `cli fleet --telemetry-dir` lays out)")
    tc.add_argument("--export", default=None, metavar="OUT",
                    help="write the Chrome-trace-event JSON here "
                         "('-' = stdout); open in https://ui.perfetto.dev")
    tc.add_argument("--tail-pct", type=float, default=99.0,
                    help="percentile cutoff for the tail-attribution "
                         "report (default: 99)")
    tc.add_argument("--json", action="store_true",
                    help="emit the attribution report as JSON")
    pf = sub.add_parser(
        "profile",
        help="summarize a jax.profiler capture directory (from "
             "POST /admin/profile, `train --profile-steps A:B` or "
             "--profile-dir) in the terminal: top ops by total time, "
             "compile split, and the x-jg-trace ids its step markers "
             "carry (OBSERVABILITY.md 'Device profiling'). For the "
             "full timeline open the trace in ui.perfetto.dev",
    )
    pf.add_argument("dir",
                    help="capture directory (the /admin/profile "
                         "response's `dir`)")
    pf.add_argument("--top", type=int, default=15,
                    help="ops to list (default 15)")
    pf.add_argument("--json", action="store_true",
                    help="emit the summary as one JSON object")
    ln = sub.add_parser(
        "lint",
        help="run the repo linter (JAX footguns JG001-JG006 + "
             "concurrency JG007-JG011 + SPMD/collective + event-schema "
             "JG012-JG018, ANALYSIS.md) over the package (or given "
             "paths); exit 1 on any unsuppressed finding; --spmd adds "
             "the runtime lockstep check of the shipped collective "
             "programs",
    )
    ln.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint (default: the "
                         "installed package source)")
    ln.add_argument("--rule", action="append", default=None,
                    metavar="JGXXX",
                    help="restrict to the given rule id(s); repeatable")
    ln.add_argument("--format", default="human",
                    choices=["human", "json", "sarif"])
    ln.add_argument("--changed-only", action="store_true",
                    help="lint only .py files git reports changed vs "
                         "--base (plus untracked); overrides positional "
                         "paths — the fast PR-scoped CI mode")
    ln.add_argument("--base", default="HEAD", metavar="REF",
                    help="git ref --changed-only diffs against "
                         "(default: HEAD; CI uses the merge base)")
    ln.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings (with their "
                         "reasons)")
    ln.add_argument("--fix-suppressions", action="store_true",
                    help="append a TODO suppression comment to every "
                         "unsuppressed finding line (backlog burndown; "
                         "reasons still have to be written by hand)")
    ln.add_argument("--spmd", action="store_true",
                    help="also run the SPMD lockstep checker "
                         "(analysis/spmd.py): record each shipped "
                         "collective program's per-process schedule at "
                         "every --spmd-world and fail with the first "
                         "divergent index if any two disagree (the CI "
                         "spmd-lockstep job)")
    ln.add_argument("--spmd-world", action="append", type=int,
                    default=None, metavar="N",
                    help="world size(s) for --spmd (repeatable; "
                         "default 2 4 8)")
    ao = sub.add_parser(
        "aot",
        help="ahead-of-time executable store (aot/, PERF.md 'Cold "
             "start'): build compiles the known jit signatures into a "
             "content-addressed store so `serve`/`serve --lm`/`train "
             "--aot` boot with zero XLA compiles; ls/gc manage it",
    )
    asub = ao.add_subparsers(dest="aot_cmd", required=True)
    ab = asub.add_parser(
        "build",
        help="lower+compile+bank the known signatures: any of a packed "
             "classifier artifact (--artifact, at the serving batch "
             "shape), a packed LM artifact (--lm-artifact, the "
             "prefill+decode pair at the engine geometry), and the "
             "single-device train step (--train). Keys match the "
             "serving/trainer load paths exactly — the same loader "
             "functions run on both sides",
    )
    ab.add_argument("--store", default=None,
                    help="store root (default: JG_AOT_STORE or "
                         "<repo>/.jax_aot)")
    ab.add_argument("--artifact", default=None,
                    help="packed classifier artifact (from `export`)")
    ab.add_argument("--batch-size", type=int, default=32,
                    help="the server's ONE compiled micro-batch shape")
    ab.add_argument("--input-shape", type=int, nargs="+",
                    default=[28, 28, 1])
    ab.add_argument("--lm-artifact", default=None,
                    help="packed LM artifact (from `lm --export`)")
    ab.add_argument("--slots", type=int, default=4)
    ab.add_argument("--page-size", type=int, default=16)
    ab.add_argument("--num-pages", type=int, default=None)
    ab.add_argument("--prefill-chunk", type=int, default=16)
    ab.add_argument("--max-len", type=int, default=None)
    ab.add_argument("--spec-decode", type=int, default=0, metavar="K",
                    help="also bank the fixed-K bf16 verify program so "
                         "`serve --lm --aot --spec-decode K` boots "
                         "zero-compile (the prefill/decode pair-miss "
                         "discipline extends to the triple)")
    ab.add_argument("--kernels", action="store_true",
                    help="bank the Pallas serving path's programs "
                         "(in-kernel page-walk attention + fused "
                         "unpack-GEMM); must match the serving flag — "
                         "kernels is part of the cache key")
    ab.add_argument("--interpret", action=argparse.BooleanOptionalAction,
                    default=None,
                    help="packed-kernel interpreter mode; must match "
                         "the serving flag (part of the cache key)")
    ab.add_argument("--train", action="store_true",
                    help="also bank the single-device train step for "
                         "the --model/--train-batch-size config")
    ab.add_argument("--model", default="bnn-mlp-large")
    ab.add_argument("--infl-ratio", type=int, default=3)
    ab.add_argument("--train-batch-size", type=int, default=64)
    ab.add_argument("--train-input-shape", type=int, nargs="+",
                    default=[28, 28, 1])
    ab.add_argument("--optimizer", default="adam")
    ab.add_argument("--lr", type=float, default=0.01)
    ab.add_argument("--loss", default="ce",
                    choices=["ce", "hinge", "sqrt_hinge"])
    ab.add_argument("--seed", type=int, default=42)
    al = asub.add_parser("ls", help="list store entries (key, size, age)")
    al.add_argument("--store", default=None)
    al.add_argument("--json", action="store_true")
    ag = asub.add_parser(
        "gc",
        help="prune entries that can never hit again: code revision no "
             "longer matching the current tree, other jax versions/"
             "backends, unknown programs, orphans, quarantined bytes",
    )
    ag.add_argument("--store", default=None)
    ag.add_argument("--dry-run", action="store_true")
    ag.add_argument("--json", action="store_true")
    return p


def _make_trainer(args, input_shape=(28, 28, 1), num_classes=10,
                  overrides=None):
    """``overrides``: TrainConfig field replacements — the elastic
    supervisor's rebuild path uses it to re-target ``data_parallel`` at
    the post-change world with ``resume`` forced on."""
    import dataclasses

    from .train import TrainConfig, Trainer

    model_kwargs = {}
    if args.model.startswith("bnn-mlp"):
        model_kwargs["infl_ratio"] = args.infl_ratio
    if num_classes != 10:
        model_kwargs["num_classes"] = num_classes
    if args.stochastic:
        model_kwargs["stochastic"] = True
    if args.xnor_scale:
        model_kwargs["scale"] = True
    if getattr(args, "dropout", None) is not None:
        model_kwargs["dropout"] = args.dropout
    config = TrainConfig(
        model=args.model,
        model_kwargs=model_kwargs,
        epochs=args.epochs,
        batch_size=args.batch_size,
        optimizer=args.optimizer,
        learning_rate=args.lr,
        lr_schedule=args.lr_schedule,
        warmup_epochs=args.warmup_epochs,
        clip_grad_norm=args.clip_grad_norm,
        seed=args.seed,
        log_interval=args.log_interval,
        loss=args.loss,
        label_smoothing=args.label_smoothing,
        augment=args.augment,
        precision=args.precision,
        backend=args.backend,
        results_path=args.results,
        timing_csv_prefix=args.timing_csv,
        checkpoint_dir=args.checkpoint_dir,
        save_all_epochs=args.save_all,
        async_checkpoint=args.async_checkpoint,
        checkpoint_backend=args.checkpoint_backend,
        native_loader=args.native_loader,
        resume=args.resume,
        data_parallel=args.dp if args.dp == "auto" else int(args.dp),
        dp_mode=args.dp_mode,
        grad_compress=args.grad_compress,
        dp_hosts=args.dp_hosts,
        compress_bucket_size=args.compress_bucket_size,
        compress_chunks=args.compress_chunks,
        pipeline_parallel=args.pp,
        pp_microbatches=args.pp_microbatches,
        pp_remat=args.pp_remat,
        tensor_parallel=args.tp,
        profile_dir=args.profile_dir,
        telemetry_dir=args.telemetry_dir,
        trace=getattr(args, "trace", None),
        sanitize=args.sanitize,
        recompile_budget=args.recompile_budget,
        nan_check_every=args.nan_check_every,
        chaos=args.chaos,
        elastic=getattr(args, "elastic", False),
        checkpoint_keep=args.checkpoint_keep,
        handle_preemption=not args.no_preemption,
        remat=args.remat,
        grad_accum=args.grad_accum,
        scan_steps=args.scan_steps,
        device_data=args.device_data,
        aot=getattr(args, "aot", False),
        aot_dir=getattr(args, "aot_dir", None),
        profile_step_window=getattr(args, "profile_steps", None),
    )
    if overrides:
        config = dataclasses.replace(config, **overrides)
    return Trainer(config, input_shape=input_shape)


def _cmd_aot(args) -> int:
    """`cli aot build|ls|gc` — manage the AOT executable store (aot/).
    build runs the SAME loader functions the serving/trainer boot paths
    use, so a banked key can never drift from the key a boot looks up."""
    import json

    from .aot import AotStore

    if args.aot_cmd == "ls":
        store = AotStore(args.store)
        rows = store.entries()
        if args.json:
            print(json.dumps(rows, default=str))
            return 0
        print(f"aot store {store.root}: {len(rows)} entr"
              f"{'y' if len(rows) == 1 else 'ies'}")
        for r in rows:
            if r.get("digest") is None:
                print(f"  {r['name']}: {r['quarantined']} quarantined "
                      "file(s) (run `aot gc`)")
                continue
            key = r.get("key", {})
            age = r.get("age_s")
            age_s = f"{age / 3600:.1f}h" if age is not None else "?"
            size = r.get("bytes")
            size_s = f"{size / 1024:.0f}KiB" if size else "?"
            flag = "  ORPHAN" if r.get("orphan") else ""
            print(f"  {r['name']}/{r['digest'][:12]}  {size_s:>8}  "
                  f"age {age_s:>6}  rev {key.get('code_rev', '?')[:12]}"
                  f"  {key.get('backend', '?')}/jax "
                  f"{key.get('jax_version', '?')}  "
                  f"avals {key.get('avals', '?')[:60]}{flag}")
        return 0

    if args.aot_cmd == "gc":
        store = AotStore(args.store)
        res = store.gc(dry_run=args.dry_run)
        if args.json:
            print(json.dumps(res))
            return 0
        verb = "would remove" if args.dry_run else "removed"
        print(f"aot gc {store.root}: {verb} {len(res['removed'])} "
              f"file(s), kept {res['kept']}")
        for r in res["removed"]:
            print(f"  {r['name']}/{r['file']}  ({r['reason']})")
        return 0

    # build
    if not (args.artifact or args.lm_artifact or args.train):
        print("aot build: nothing to build — pass --artifact, "
              "--lm-artifact and/or --train", file=sys.stderr)
        return 2
    import jax

    from .aot import load_packed_aot, load_paged_lm_decoder_aot

    store = AotStore(args.store)
    interpret = (
        jax.default_backend() != "tpu"
        if args.interpret is None else args.interpret
    )
    built = []
    if args.artifact:
        _, info, meta = load_packed_aot(
            args.artifact,
            batch_size=args.batch_size,
            input_shape=tuple(args.input_shape),
            interpret=interpret,
            store=store,
        )
        built.append({
            "program": "classifier_predict", "artifact": args.artifact,
            "family": info.get("family"), **meta,
        })
    if args.lm_artifact:
        _, info, meta = load_paged_lm_decoder_aot(
            args.lm_artifact,
            slots=args.slots,
            page_size=args.page_size,
            num_pages=args.num_pages,
            prefill_chunk=args.prefill_chunk,
            max_len=args.max_len,
            spec_k=args.spec_decode,
            interpret=interpret,
            kernels=args.kernels,
            store=store,
        )
        built.append({
            "program": "lm_prefill+lm_decode",
            "artifact": args.lm_artifact, **meta,
        })
    if args.train:
        from .train import TrainConfig, Trainer

        model_kwargs = (
            {"infl_ratio": args.infl_ratio}
            if args.model.startswith("bnn-mlp") else {}
        )
        trainer = Trainer(
            TrainConfig(
                model=args.model, model_kwargs=model_kwargs,
                batch_size=args.train_batch_size,
                optimizer=args.optimizer, learning_rate=args.lr,
                loss=args.loss, seed=args.seed,
                aot=True, aot_dir=store.root,
            ),
            input_shape=tuple(args.train_input_shape),
        )
        built.append({
            "program": "train_step", "model": args.model,
            "status": trainer.aot_status,
        })
    # "hit" = the entry was already banked and verified loadable —
    # build is idempotent.
    print(json.dumps({"store": store.root, "built": built}))
    return 0


def _fit_elastic(args, data, first_trainer):
    """Run a fit under the in-process elastic supervisor (RESILIENCE.md
    "Elastic membership"): chaos ``worker_lost``/``worker_restore``
    shrinks/regrows the DP mesh with state re-placed from the newest
    digest-verified checkpoint generation — no job restart, no exit 75,
    except for a REAL scheduler signal, which still vacates with the
    resumable exit code."""
    from .obs import Telemetry
    from .resilience import RetryPolicy
    from .resilience.elastic import run_elastic

    first = [first_trainer]

    def make_tr(world):
        if world is None and first:
            return first.pop()
        return _make_trainer(
            args, input_shape=data.input_shape,
            num_classes=getattr(data, "n_classes", 10),
            overrides={"data_parallel": world, "resume": True},
        )

    # The supervisor's remesh/restart events append to the same
    # events.jsonl the trainers write (each seals its own log before
    # the supervisor emits) — the chaos_smoke policy-telemetry pattern.
    sup_tel = (
        Telemetry(args.telemetry_dir, heartbeat=False)
        if args.telemetry_dir else None
    )
    try:
        return _fit_resumable(lambda: run_elastic(
            make_tr, lambda t: t.fit(data),
            policy=RetryPolicy(seed=args.seed),
            telemetry=sup_tel,
        ))
    finally:
        if sup_tel is not None:
            sup_tel.close()


def _fit_resumable(fit_fn):
    """Run a fit under the preemption contract: a graceful stop maps to
    the distinct EX_TEMPFAIL exit a supervisor reads as "reschedule me",
    not "crashed" (RESILIENCE.md). Returns (exit_code, history) —
    exit_code 0 means the fit ran to completion."""
    from .resilience import Preempted

    try:
        return 0, fit_fn()
    except Preempted as e:
        log.warning(
            "%s; state checkpointed — rerun with --resume to continue "
            "(exit %d)", e, e.exit_code,
        )
        return e.exit_code, None


def _honor_platform_env() -> str | None:
    """Re-assert JAX_PLATFORMS over any sitecustomize that flipped the jax
    config at interpreter start (some images register experimental PJRT
    plugins that way). Without this, ``JAX_PLATFORMS=cpu cli train ...``
    can silently run — or hang dialing — a remote backend.

    Must run before *anything* that can initialize a backend (parser
    building and logging setup pull package imports that may). Returns the
    platform that could NOT be pinned (for a deferred warning once logging
    is configured), or None on success/no-op."""
    import os

    from .utils.platform import pin_platform

    plat = os.environ.get("JAX_PLATFORMS")
    if plat and not pin_platform(plat):
        return plat
    return None


def main(argv=None) -> int:
    repin_failed = _honor_platform_env()
    parser = build_parser()
    args = parser.parse_args(argv)

    if args.cmd == "lint":
        # Pure host-side AST analysis: no jax backend, no logging setup.
        import os

        from .analysis.lint import (
            changed_py_files,
            fix_suppressions,
            format_human,
            format_json,
            format_sarif,
            run_paths,
        )

        if args.changed_only:
            try:
                paths = changed_py_files(args.base)
            except RuntimeError as e:
                print(f"lint --changed-only: {e}", file=sys.stderr)
                return 2
            if not paths:
                print("lint --changed-only: no changed .py files",
                      file=sys.stderr)
                print(format_json([]) if args.format == "json"
                      else format_sarif([]) if args.format == "sarif"
                      else "0 finding(s), 0 suppressed")
                return 0
        else:
            paths = args.paths or [
                os.path.dirname(os.path.abspath(__file__))
            ]
        findings = run_paths(paths, rule_ids=args.rule)
        if args.fix_suppressions:
            edited = fix_suppressions(findings)
            print(f"annotated {edited} line(s) with TODO suppressions",
                  file=sys.stderr)
            findings = run_paths(paths, rule_ids=args.rule)
        if args.format == "json":
            print(format_json(findings))
        elif args.format == "sarif":
            print(format_sarif(findings))
        else:
            print(format_human(
                findings, show_suppressed=args.show_suppressed
            ))
        rc = 1 if any(not f.suppressed for f in findings) else 0
        if args.spmd:
            # The runtime half: jax imports only behind the flag so the
            # static path stays backend-free.
            from .analysis.spmd import LockstepError, verify_shipped

            worlds = tuple(args.spmd_world or (2, 4, 8))
            try:
                report = verify_shipped(worlds=worlds)
            except LockstepError as e:
                print(f"spmd-lockstep: FAIL\n{e}", file=sys.stderr)
                return 1
            for row in report:
                print(
                    f"spmd-lockstep: {row['program']} world "
                    f"{row['world']}: {row['n_collectives']} "
                    "collectives in lockstep"
                )
        return rc

    if args.cmd == "aot":
        return _cmd_aot(args)

    if args.cmd == "telemetry":
        # Pure host-side log reading: no jax backend, no logging setup
        # (stdout stays the report).
        import json
        import os

        from .obs import render_table, summarize
        from .obs.telemetry import EVENTS_FILE

        if args.fleet:
            from .obs import render_fleet_table, summarize_fleet

            root = args.log
            if os.path.isfile(root):
                root = os.path.dirname(root) or "."
            try:
                fleet_summary = summarize_fleet(root)
            except FileNotFoundError:
                print(
                    f"no fleet event log under {root} (expected "
                    f"{EVENTS_FILE} plus replica subdirectories)",
                    file=sys.stderr,
                )
                return 2
            print(json.dumps(fleet_summary) if args.json
                  else render_fleet_table(fleet_summary))
            return 0
        path = args.log
        if os.path.isdir(path):
            path = os.path.join(path, EVENTS_FILE)
        try:
            summary = summarize(path)
        except FileNotFoundError:
            print(f"no event log at {path}", file=sys.stderr)
            return 2
        print(json.dumps(summary) if args.json else render_table(summary))
        return 0

    if args.cmd == "trace":
        # Pure host-side log reading, like `telemetry`: no jax backend.
        import json
        import os

        from .obs.telemetry import EVENTS_FILE
        from .obs.trace import (
            load_spans,
            render_attribution,
            stitch_spans,
            tail_attribution,
            to_chrome_trace,
        )

        groups = {}
        for given in args.log:
            path = given
            if os.path.isdir(path):
                path = os.path.join(path, EVENTS_FILE)
                name = os.path.basename(os.path.abspath(given))
            else:
                name = os.path.basename(
                    os.path.dirname(os.path.abspath(path))
                ) or given
            try:
                loaded = load_spans(path)
            except FileNotFoundError:
                print(f"no event log at {path}", file=sys.stderr)
                return 2
            groups[name] = (path, loaded)
        if len(groups) > 1:
            # Multi-directory fleet mode: stitch replica request trees
            # under their router dispatch spans (time-shifted to the
            # router's clock lane) before attributing the tail.
            stitched = stitch_spans(
                {name: spans for name, (_, spans) in groups.items()}
            )
            spans = stitched["spans"]
            path = " + ".join(sorted(groups))
            print(
                f"stitched {stitched['joined']}/"
                f"{stitched['replica_roots']} replica request tree(s) "
                f"across {len(groups)} log(s)",
                file=sys.stderr,
            )
            if stitched["unjoined"]:
                print(
                    f"  {len(stitched['unjoined'])} replica root(s) "
                    "had no matching dispatch span (untraced router, "
                    "or dir names not matching replica ids)",
                    file=sys.stderr,
                )
        else:
            (path, spans), = groups.values()
        if not spans:
            print(
                f"no span events in {path} — was the run traced? "
                "(--trace / JG_TRACE=1, OBSERVABILITY.md 'Tracing')",
                file=sys.stderr,
            )
            return 2
        if args.export:
            if len(groups) > 1:
                # One pid lane per process, stitched clock preserved.
                chrome = {"traceEvents": [], "displayTimeUnit": "ms"}
                for pid, name in enumerate(sorted(groups)):
                    rows = [
                        s for s in spans
                        if (s.get("attrs") or {}).get("process") == name
                    ]
                    sub = to_chrome_trace(
                        rows, pid=pid, process_name=name,
                    )
                    chrome["traceEvents"] += sub["traceEvents"]
            else:
                chrome = to_chrome_trace(
                    spans, process_name=os.path.basename(
                        os.path.dirname(os.path.abspath(path))
                    ),
                )
            if args.export == "-":
                print(json.dumps(chrome))
                return 0          # stdout is the export, no report
            with open(args.export, "w") as f:
                json.dump(chrome, f)
            print(
                f"wrote {len(chrome['traceEvents'])} trace events "
                f"to {args.export} (open in https://ui.perfetto.dev)",
                file=sys.stderr,
            )
        report = tail_attribution(spans, pct=args.tail_pct)
        if report["n_requests"] == 0:
            # No request roots (e.g. a traced TRAINING run): report
            # per-kind totals instead of an empty tail table.
            from .obs.trace import span_kind_totals

            totals = span_kind_totals(spans)
            if args.json:
                print(json.dumps({**report, "kind_totals": totals}))
                return 0
            print(f"no request spans in {path}; per-kind totals over "
                  f"{len(spans)} span(s):")
            for kind, row in totals.items():
                print(f"  {kind:<16} x{row['count']:<6} "
                      f"{row['total_ms']:>12.3f} ms")
            return 0
        print(json.dumps(report) if args.json
              else render_attribution(report))
        return 0

    if args.cmd == "profile":
        # Pure host-side capture reading (gzip + json): no jax backend.
        import json

        from .obs import render_capture_summary, summarize_capture

        try:
            summary = summarize_capture(args.dir, top=args.top)
        except FileNotFoundError as e:
            print(str(e), file=sys.stderr)
            return 2
        print(json.dumps(summary) if args.json
              else render_capture_summary(summary))
        return 0

    if args.cmd == "lm":
        from .utils import setup_logging

        setup_logging(args.log_file)
        if repin_failed:
            log.warning(
                "could not re-pin jax platform to %r (backend already "
                "initialized)", repin_failed,
            )
        if args.load:
            # Serve a packed artifact: KV-cache decode, no training.
            import jax as _jax
            import jax.numpy as _jnp
            from flax import serialization

            from .infer_transformer import generate, make_lm_decoder

            with open(args.load, "rb") as f:
                frozen = serialization.msgpack_restore(f.read())
            if frozen.get("info", {}).get("kind") != "lm":
                log.error("%s is not a packed LM artifact", args.load)
                return 2
            # --sample keeps its training-mode default of 0 ("none"), so
            # an unset value means "a reasonable demo length" here; an
            # explicit negative is an input error, reported cleanly.
            if args.sample < 0:
                log.error("--sample must be >= 0, got %d", args.sample)
                return 2
            n = args.sample if args.sample > 0 else 64
            prompt_bytes = (args.prompt or "\n").encode("utf-8")
            vocab = int(frozen["tok_embed"].shape[0])
            prompt = _jnp.asarray(
                [[b % vocab for b in prompt_bytes]], _jnp.int32
            )
            max_len = int(frozen["pos_embed"].shape[1])
            if prompt.shape[1] >= max_len:
                log.error(
                    "prompt (%d tokens) fills the artifact's trained "
                    "window (max_len %d)", prompt.shape[1], max_len,
                )
                return 2
            if prompt.shape[1] + n > max_len:
                n = max_len - prompt.shape[1]
                log.warning(
                    "clamped --sample to %d: the artifact's fixed "
                    "positional window is max_len=%d", n, max_len,
                )
            interpret = (
                _jax.default_backend() != "tpu"
                if args.interpret is None else args.interpret
            )
            # Build the decoder explicitly (one-decoder-per-artifact
            # rule): generate(decoder=None) would log the rebuild
            # warning and count toward lm_decoder_rebuilds_total, a
            # signal reserved for accidental hot-path rebuilds.
            toks = generate(
                frozen, prompt, n, temperature=args.temperature,
                rng=_jax.random.PRNGKey(args.seed), interpret=interpret,
                decoder=make_lm_decoder(frozen, interpret=interpret),
            )
            out = [int(t) for t in toks[0, prompt.shape[1]:]]
            if vocab == 256:  # byte-level: show as text
                text = bytes(out).decode("utf-8", errors="replace")
                print(f"sample ({n} bytes, T={args.temperature}): {text!r}")
            else:
                print(f"sample ({n} tokens, T={args.temperature}): {out}")
            return 0

        from .examples.lm_demo import run as lm_run

        history, _ = lm_run(
            steps=args.steps, seq_len=args.seq_len, batch=args.batch_size,
            embed_dim=args.embed_dim, depth=args.depth,
            num_heads=args.num_heads, lr=args.lr, seed=args.seed,
            attention=args.attention, ring=args.ring, corpus=args.corpus,
            pp=args.pp, log_every=args.log_interval, sample=args.sample,
            temperature=args.temperature, export=args.export,
        )
        log.info("lm final next-token loss: %.4f", history[-1])
        return 0

    if args.cmd == "fleet":
        if args.action:
            # `fleet explain DIR` — render the control-plane decision
            # timeline (autoscaler, breakers, rollouts, SLO alerts)
            # out of a fleet telemetry dir, no server needed.
            if args.action[0] != "explain" or len(args.action) != 2:
                parser.error(
                    "fleet: unknown action %r (only `fleet explain "
                    "DIR` is supported)" % " ".join(args.action)
                )
            import json
            import os

            from .obs import decision_timeline, read_events, \
                render_decision_timeline
            from .obs.telemetry import EVENTS_FILE

            path = args.action[1]
            if os.path.isdir(path):
                path = os.path.join(path, EVENTS_FILE)
            try:
                events = list(read_events(path))
            except FileNotFoundError:
                print(f"no event log at {path}", file=sys.stderr)
                return 2
            rows = decision_timeline(events)
            if args.json:
                print(json.dumps(rows))
            else:
                print(render_decision_timeline(
                    rows, title=f"fleet decision timeline: {path}",
                ))
            return 0
        if not args.artifact:
            parser.error(
                "fleet: --artifact is required to serve "
                "(or use `fleet explain DIR`)"
            )
        # Control plane only: the fleet process never touches jax —
        # inference happens in the replica subprocesses it spawns.
        from .utils import setup_logging

        setup_logging(args.log_file)
        from .serve.fleet import FleetConfig, FleetServer

        rflags = []
        if args.batch_size is not None:
            rflags += ["--batch-size", str(args.batch_size)]
        if args.queue_depth is not None:
            rflags += ["--queue-depth", str(args.queue_depth)]
        if args.stall_timeout_s is not None:
            rflags += ["--stall-timeout-s", str(args.stall_timeout_s)]
        if args.chaos:
            rflags += ["--chaos", args.chaos]
        if args.interpret is not None:
            rflags += ["--interpret" if args.interpret
                       else "--no-interpret"]
        if args.aot:
            rflags += ["--aot"]
        if args.aot_dir:
            rflags += ["--aot-dir", args.aot_dir]
        if args.seed:
            rflags += ["--seed", str(args.seed)]
        rflags += args.replica_arg or []
        fleet = FleetServer(FleetConfig(
            artifact=args.artifact,
            host=args.host,
            port=args.port,
            replicas=args.replicas,
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            lm=args.lm,
            page_size=args.page_size,
            input_shape=tuple(args.input_shape),
            default_deadline_ms=args.deadline_ms,
            max_attempts=args.max_attempts,
            probe_interval_s=args.probe_interval_s,
            breaker_threshold=args.breaker_threshold,
            breaker_reset_s=args.breaker_reset_s,
            boot_timeout_s=args.boot_timeout_s,
            autoscale=args.autoscale,
            queue_high=args.queue_high,
            queue_low=args.queue_low,
            sustain_s=args.sustain_s,
            cooldown_s=args.cooldown_s,
            drain_timeout_s=args.drain_timeout_s,
            staging_dir=args.staging_dir,
            telemetry_dir=args.telemetry_dir,
            trace=args.trace,
            events_max_bytes=args.events_max_bytes,
            scrape_interval_s=args.scrape_interval_s,
            slo=args.slo,
            slo_fast_window_s=args.slo_fast_window_s,
            slo_slow_window_s=args.slo_slow_window_s,
            seed=args.seed,
            replica_flags=rflags,
        ))
        return fleet.run()

    if args.cmd == "serve":
        from .utils import setup_logging

        setup_logging(args.log_file)
        if repin_failed:
            log.warning(
                "could not re-pin jax platform to %r (backend already "
                "initialized)", repin_failed,
            )
        if args.lm:
            from .serve.lm import LMServeConfig, LMServer

            lm_server = LMServer(LMServeConfig(
                artifact=args.artifact,
                host=args.host,
                port=args.port,
                slots=args.slots,
                page_size=args.page_size,
                num_pages=args.num_pages,
                prefill_chunk=args.prefill_chunk,
                max_len=args.max_len,
                queue_depth=args.queue_depth,
                default_deadline_ms=(
                    30000.0 if args.deadline_ms is None
                    else args.deadline_ms
                ),
                default_max_new_tokens=args.max_new_tokens,
                max_prompt_tokens=args.max_prompt_tokens,
                drain_timeout_s=args.drain_timeout_s,
                telemetry_dir=args.telemetry_dir,
                chaos=args.chaos,
                seed=args.seed,
                interpret=args.interpret,
                aot=args.aot,
                aot_dir=args.aot_dir,
                trace=args.trace,
                prefix_cache=args.prefix_cache,
                spec_decode=args.spec_decode,
                kernels=args.kernels,
                costs=args.costs,
                events_max_bytes=args.events_max_bytes,
            ))
            return lm_server.run()

        from .serve import PackedInferenceServer, ServeConfig

        server = PackedInferenceServer(ServeConfig(
            artifact=args.artifact,
            host=args.host,
            port=args.port,
            batch_size=args.batch_size,
            queue_depth=args.queue_depth,
            default_deadline_ms=(
                1000.0 if args.deadline_ms is None else args.deadline_ms
            ),
            linger_ms=args.linger_ms,
            stall_timeout_s=args.stall_timeout_s,
            breaker_threshold=args.breaker_threshold,
            breaker_reset_s=args.breaker_reset_s,
            breaker_probes=args.breaker_probes,
            drain_timeout_s=args.drain_timeout_s,
            input_shape=tuple(args.input_shape),
            telemetry_dir=args.telemetry_dir,
            chaos=args.chaos,
            seed=args.seed,
            interpret=args.interpret,
            aot=args.aot,
            aot_dir=args.aot_dir,
            trace=args.trace,
            costs=args.costs,
            events_max_bytes=args.events_max_bytes,
        ))
        return server.run()

    if args.norm is not None and args.norm not in (
        "half", "none",
        {"mnist": "mnist", "cifar10": "cifar",
         "imagenet": "imagenet"}[args.dataset],
    ):
        parser.error(
            f"--norm {args.norm} is not valid for --dataset {args.dataset}"
        )

    from .utils import setup_logging

    setup_logging(args.log_file)
    if repin_failed:
        log.warning(
            "could not re-pin jax platform to %r (backend already "
            "initialized)", repin_failed,
        )

    if args.nodes > 1 or args.coordinator:
        from .parallel import initialize_multihost

        initialize_multihost(
            coordinator_address=args.coordinator,
            num_processes=args.nodes,
            process_id=args.node_rank,
            initialization_timeout_s=args.init_timeout,
            retries=args.init_retries,
        )

    import jax

    from .data import load_dataset

    if getattr(args, "stream", False):
        if args.dataset != "imagenet":
            log.error("--stream is for `train --dataset imagenet`")
            return 2
        import numpy as np

        from .data import open_imagenet_stream
        from .data.common import ImageClassData

        norm_kw = {"norm": args.norm} if args.norm else {}
        stream = open_imagenet_stream(
            args.data_dir, "train", image_size=args.image_size, **norm_kw
        )
        if stream is None:
            log.error(
                "--stream needs an on-disk ImageNet layout under %s "
                "(train/<wnid>/ dirs or <wnid>.tar files)", args.data_dir,
            )
            return 2
        # val subset for the eval pass: the val split indexed against the
        # TRAIN stream's wnid label space (reusing the index in hand —
        # no second walk of the train split). Without a val/ split, train
        # without eval rather than fabricating a degenerate test set.
        val = open_imagenet_stream(
            args.data_dir, "val", image_size=args.image_size,
            wnids=stream.index.wnids, **norm_kw,
        )
        if val is None:
            log.warning(
                "no val/ split under %s: training without eval (no "
                "best-checkpoint tracking)", args.data_dir,
            )
            eval_data = None
        else:
            vx, vy = val.materialize(2048)
            eval_data = ImageClassData(
                np.zeros((1, *vx.shape[1:]), np.float32),
                np.zeros(1, np.int32), vx, vy,
                source="imagenet", name="imagenet",
                n_classes=stream.n_classes,
            )
        log.info(
            "streaming imagenet: %d train images (never materialized), "
            "%s val, %d classes", len(stream),
            len(eval_data.test_labels) if eval_data is not None else "no",
            stream.n_classes,
        )
        trainer = _make_trainer(
            args, input_shape=(args.image_size, args.image_size, 3),
            num_classes=stream.n_classes,
        )
        rc, history = _fit_resumable(
            lambda: trainer.fit_stream(stream, eval_data=eval_data)
        )
        if rc:
            return rc
        log.info("final: %s", history[-1] if history else {})
        return 0

    kwargs = {}
    if args.norm is not None:
        kwargs["norm"] = args.norm
    if args.synthetic_sizes is not None:
        kwargs["synthetic_sizes"] = tuple(args.synthetic_sizes)
    if args.dataset == "imagenet":
        kwargs["image_size"] = args.image_size
    data = load_dataset(args.dataset, args.data_dir, **kwargs)
    log.info("data source: %s/%s (%d train / %d test)", args.dataset,
             data.source, len(data.train_labels), len(data.test_labels))

    if args.cmd == "infer":
        import json
        import time as _time

        import jax.numpy as jnp
        import numpy as np

        from .infer import load_packed

        interpret = (
            jax.default_backend() != "tpu"
            if args.interpret is None else args.interpret
        )
        from .obs import default_registry, get_tracker

        fn, info = load_packed(args.artifact, interpret=interpret)
        bs = args.batch_size
        registry = default_registry()
        batch_hist = registry.histogram(
            "infer_batch_seconds", "packed-serving full-batch latency"
        )
        examples_ctr = registry.counter(
            "infer_examples_total", "examples served by packed inference"
        )
        tracker = get_tracker()
        compiles_before = tracker.mark()
        # Warm the full-batch program so reported latency is serving
        # time, not jit/Mosaic compile time (the trailing partial batch
        # compiles its own shape; it is excluded from the average).
        np.asarray(fn(jnp.asarray(data.test_images[:bs])))
        correct = total = 0
        t_sum = 0.0
        full_batches = 0
        for start in range(0, len(data.test_labels), bs):
            x = jnp.asarray(data.test_images[start : start + bs])
            y = np.asarray(data.test_labels[start : start + bs])
            t0 = _time.perf_counter()
            preds = np.asarray(fn(x)).argmax(-1)  # host fetch = sync
            if len(y) == bs:
                dt = _time.perf_counter() - t0
                t_sum += dt
                full_batches += 1
                batch_hist.observe(dt)
            correct += int((preds == y).sum())
            total += len(y)
            examples_ctr.inc(len(y))
        out = {
            "artifact": args.artifact,
            "family": info.get("family"),
            "test_acc": round(100.0 * correct / max(total, 1), 2),
            "n_examples": total,
            "avg_batch_latency_ms": round(
                t_sum / max(full_batches, 1) * 1e3, 3
            ),
            "compression": info.get("compression"),
            "interpret": interpret,
        }
        if args.telemetry_dir:
            # Serving runs share the training event schema, so one
            # `telemetry` read covers both sides of a model's life.
            from .obs import Telemetry

            with Telemetry(args.telemetry_dir, heartbeat=False) as tel:
                tel.manifest(config=vars(args))
                tel.emit(
                    "infer",
                    **out,
                    p50_batch_s=batch_hist.percentile(50),
                    p95_batch_s=batch_hist.percentile(95),
                    recompiles=tracker.count - compiles_before,
                )
        log.info("packed inference: %s", out)
        print(json.dumps(out))
        return 0

    trainer = _make_trainer(
        args, input_shape=data.input_shape,
        num_classes=getattr(data, "n_classes", 10),
    )

    if args.cmd == "train":
        from .parallel.distributed import detect_multihost

        if getattr(args, "elastic", False) and detect_multihost() is None:
            rc, history = _fit_elastic(args, data, trainer)
        else:
            # Plain resumable contract — including multihost elastic
            # RANK processes (JG_MH_* set): membership is supervised by
            # the PARENT (resilience.multihost.run_elastic_multihost),
            # so a host-loss/regrow Preempted must surface as exit 75
            # for it, not be "resumed" by an in-process run_elastic
            # that cannot rebuild the TCP world.
            rc, history = _fit_resumable(lambda: trainer.fit(data))
        if rc:
            return rc
        final = history[-1] if history else {}
        log.info("final: %s", final)
        return 0

    if args.cmd == "eval":
        if not args.checkpoint_dir:
            log.error("eval requires --checkpoint-dir")
            return 2
        trainer.state = trainer.restore(args.checkpoint_dir, best=args.best)
        metrics = trainer.evaluate(data)
        # fit() owns the close in training runs; standalone eval must
        # seal its own log (run_end + heartbeat stop).
        trainer.telemetry.emit("eval", **metrics)
        trainer.telemetry.close()
        log.info("eval: %s", metrics)
        print(metrics)
        return 0

    if args.cmd == "export":
        if not args.checkpoint_dir:
            log.error("export requires --checkpoint-dir")
            return 2
        from .infer import export_packed

        trainer.state = trainer.restore(args.checkpoint_dir, best=args.best)
        info = export_packed(
            trainer.model,
            {
                "params": trainer.state.params,
                "batch_stats": trainer.state.batch_stats,
            },
            args.out,
            input_shape=data.input_shape,
        )
        # info nests under its own field: transformer artifacts carry a
        # "kind" key that would collide with the event envelope's kind
        # (same convention as the serve/ reload event).
        trainer.telemetry.emit("export", out=args.out, info=dict(info))
        trainer.telemetry.close()
        log.info("exported packed model to %s: %s", args.out, info)
        print({"out": args.out, **info})
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
