"""Packed 1-bit serving for the transformer families (BinarizedTransformer
vit + BinarizedLM) — completing frozen-inference coverage of the model zoo
(infer.py: MLP; infer_conv.py: CNN/ResNet; here: attention models).

No reference counterpart (the reference stops at MLP/CNN training scripts
— SURVEY §2.2). What freezes and what stays fp32 follows the family's own
numerics contract (models/transformer.py): every Binarized projection
(patch/q/k/v/out/mlp) drops its fp32 latent master and keeps only ±1
weights — hidden projections pre-packed to 1-bit bitplanes
(ops.prepack_weights, 32x smaller than fp32) and run on the packed XNOR
kernel, which is the bandwidth-bound small-batch serving win (PERF.md §3)
— while LayerNorm, the softmax attention core, residuals, embeddings and
the head stay fp32 exactly as they do in the live eval forward.

Unlike the MLP/conv families there is no BN→threshold folding here:
LayerNorm statistics are data-dependent at inference (they normalize over
the feature axis per token, not over a frozen batch population), so the
frozen graph keeps real LNs and binarizes activations on the fly with the
same deterministic sign the live eval path uses
(models/layers._binarize_activations with no rng).

The attention core always runs the exact-softmax oracle
(models/transformer._attend_xla) — bit-identical to live models built with
attention="xla" (the family default). Freezing a flash-attention-trained
model serves fine but can differ by sign flips on few-ulp-boundary
activations (the repo's attn_core numerics policy); freeze/compare against
an attention="xla" twin if exact equality matters. The same caveat covers
the bf16 backend's patch embedding: it casts raw pixels to bf16 (an
AMP-style trade, models/layers._layer_backend) while the frozen graph dots
them in fp32 — equality tests pin backend="xla".
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

log = logging.getLogger(__name__)

from .models.transformer import (
    BinarizedLM,
    BinarizedTransformer,
    _attend_xla,
)
from .ops.binarize import binarize_ste
from .ops.xnor_gemm import (
    prepack_weights,
    xnor_matmul_fused_unpack,
    xnor_matmul_packed,
)


def _freeze_dense(params: Dict, scale: bool) -> Dict[str, Any]:
    """One hidden BinarizedDense -> packed bitplanes + fp32 bias (+ the
    XNOR-Net alpha, precomputed from the latent master it replaces)."""
    kernel = params["kernel"]
    wp, k, n = prepack_weights(binarize_ste(kernel))
    out = {"wp": wp, "k": k, "n": n, "bias": params["bias"]}
    if scale:
        out["alpha"] = jnp.abs(kernel).mean(axis=0)
    return out


def _freeze_dense_fp32(params: Dict) -> Dict[str, Any]:
    """One fp32 nn.Dense, carried as-is: the partial-binarization recipe
    (RESULTS.md ablation — fp32 q/k/v/out, binary MLP) keeps attention
    projections dense, so the artifact stores their fp32 kernels and the
    serving graph runs plain matmuls for them. Marker: 'kernel' instead
    of 'wp'."""
    return {"kernel": params["kernel"], "bias": params["bias"]}


def _dense_fn(
    layer: Dict[str, Any], interpret: bool, fused: bool = False
) -> Callable:
    """Layer closure dispatch: packed 1-bit ('wp') or carried fp32
    ('kernel' — partial binarization). ``fused`` selects the fused
    unpack-GEMM carry of the packed weights (kernel serving path)."""
    if "wp" in layer:
        return _packed_dense_fn(layer, interpret, fused)
    kernel = jnp.asarray(layer["kernel"], jnp.float32)
    bias = jnp.asarray(layer["bias"], jnp.float32)
    return lambda x: jnp.dot(x, kernel) + bias


#: M at which the kernel serving path switches the packed-weight GEMM
#: from the XNOR-popcount carry to the fused bitplane-unpack carry.
#: Same discipline as the PERF.md §3 packed-vs-dense crossover, one
#: level down: below it (the S-slot decode step) popcount's ~K/32
#: word ops per output beat anything that expands bitplanes; at and
#: above it (prefill chunks, S*K verify windows) the fused kernel's
#: in-VMEM unpack feeds the MXU full (bm, bn) tiles. Both carries
#: stream the SAME packed words — HBM stays 1/32 byte/param either
#: way — and both are exact on the ±1 domain, so the choice cannot
#: move logits. M is static per compiled program, so the pick is
#: burned in at trace time (no shape-dependent recompiles).
FUSED_UNPACK_MIN_M = 16


def _packed_dense_fn(
    layer: Dict[str, Any], interpret: bool, fused: bool = False
) -> Callable:
    """sign(x) @ packed-W (+ alpha) + b over any leading shape.

    ``fused=False`` always runs the XNOR-popcount kernel on packed
    activations; ``fused=True`` (the kernel serving path) picks per
    dispatch shape: popcount below ``FUSED_UNPACK_MIN_M`` rows,
    ``xnor_matmul_fused_unpack`` — same packed weights, bitplanes
    expanded in-kernel per K tile and hit with MXU dots — at or above
    it. All carries are exact integer GEMMs on the ±1 domain, so the
    kernel-flag flip cannot move logits."""
    wp = jnp.asarray(layer["wp"])
    k, n = int(layer["k"]), int(layer["n"])
    bias = jnp.asarray(layer["bias"], jnp.float32)
    alpha = (
        jnp.asarray(layer["alpha"], jnp.float32)
        if layer.get("alpha") is not None else None
    )

    def fn(x: jnp.ndarray) -> jnp.ndarray:
        bits = binarize_ste(x)
        lead = bits.shape[:-1]
        flat = bits.reshape(-1, k)
        use_fused = fused and flat.shape[0] >= FUSED_UNPACK_MIN_M
        matmul = xnor_matmul_fused_unpack if use_fused else xnor_matmul_packed
        y = matmul(
            flat, wp, k, n, interpret=interpret
        )
        y = y.reshape(*lead, n)
        if alpha is not None:
            y = y * alpha
        return y + bias

    return fn


def _dense_bf16_fn(layer: Dict[str, Any]) -> Callable:
    """The SAME weights as :func:`_packed_dense_fn`, carried dense:
    bitplanes unpacked to a ±1 bf16 kernel, GEMM accumulated in fp32.

    This is the speculative-decode **verifier** format (PERF.md §3's
    crossover: packed bitplanes win the bandwidth-bound small-M decode
    regime, dense bf16 wins the large-M batched regime the fixed-K
    verify dispatch lives in). ±1 is exact in bf16 and the fp32
    accumulation of ±1 products is exact for any summation order, so
    the projection output is numerically IDENTICAL to the packed
    kernel's — draft and verifier disagree only through reduction-order
    ulps in LN/attention, which is what keeps greedy draft acceptance
    near 1. Carried-fp32 layers (partial binarization) stay fp32 — they
    have no packed twin to be exact against."""
    if "wp" not in layer:
        kernel = jnp.asarray(layer["kernel"], jnp.float32)
        bias = jnp.asarray(layer["bias"], jnp.float32)
        return lambda x: jnp.dot(x, kernel) + bias
    from .ops.bitpack import unpack_bits

    k, n = int(layer["k"]), int(layer["n"])
    w = unpack_bits(jnp.asarray(layer["wp"]).T, k)[:n].T   # (k, n) ±1
    w_bf16 = w.astype(jnp.bfloat16)
    bias = jnp.asarray(layer["bias"], jnp.float32)
    alpha = (
        jnp.asarray(layer["alpha"], jnp.float32)
        if layer.get("alpha") is not None else None
    )

    def fn(x: jnp.ndarray) -> jnp.ndarray:
        bits = binarize_ste(x).astype(jnp.bfloat16)
        lead = bits.shape[:-1]
        y = jnp.dot(
            bits.reshape(-1, k), w_bf16,
            preferred_element_type=jnp.float32,
        ).reshape(*lead, n)
        if alpha is not None:
            y = y * alpha
        return y + bias

    return fn


def _ln_fn(params: Dict) -> Callable:
    """The real flax LayerNorm over frozen scale/bias — applied as a
    module so the frozen graph's normalization is the live graph's."""
    ln = nn.LayerNorm()
    variables = {"params": {
        "scale": jnp.asarray(params["scale"], jnp.float32),
        "bias": jnp.asarray(params["bias"], jnp.float32),
    }}
    return lambda y: ln.apply(variables, y)


def _check_freezable(model) -> None:
    if not model.binarized:
        raise ValueError(
            "packed freezing needs binarized weights; the fp32 twins "
            "have none to pack (serve them as live models)"
        )
    if model.stochastic:
        raise ValueError(
            "stochastic activation binarization is a train-time feature; "
            "freeze the deterministic eval path"
        )
    if model.attention_fn is not None:
        raise ValueError(
            "attention_fn (ring/SP) is a training-topology override; "
            "freeze the plain single-device model"
        )


def _freeze_blocks(params: Dict, depth: int, scale: bool) -> list:
    """Frozen tensors for TransformerBlock_0..depth-1 (flax auto-names:
    attention projections BinarizedSelfAttention_0/BinarizedDense_0..3 in
    q,k,v,out order; MLP projections BinarizedDense_0..1 at block level —
    models/transformer.py:153-186)."""
    blocks = []
    for i in range(depth):
        bp = params[f"TransformerBlock_{i}"]
        attn = bp["BinarizedSelfAttention_0"]
        if "Dense_0" in attn:
            # binarized_attention=False: fp32 q/k/v/out (flax auto-names
            # nn.Dense as Dense_0..3 in the same q,k,v,out order)
            proj = [
                _freeze_dense_fp32(attn[f"Dense_{j}"]) for j in range(4)
            ]
        else:
            proj = [
                _freeze_dense(attn[f"BinarizedDense_{j}"], scale)
                for j in range(4)
            ]
        blocks.append({
            "ln_attn": dict(bp["ln_attn"]),
            "q": proj[0],
            "k": proj[1],
            "v": proj[2],
            "out": proj[3],
            "ln_mlp": dict(bp["ln_mlp"]),
            "mlp1": _freeze_dense(bp["BinarizedDense_0"], scale),
            "mlp2": _freeze_dense(bp["BinarizedDense_1"], scale),
        })
    return blocks


def _binarized_kernel_bytes(params: Dict) -> int:
    """fp32 bytes of every Binarized* latent kernel in the tree — the
    masters the frozen artifact drops."""
    total = 0
    for name, sub in params.items():
        if not isinstance(sub, dict):
            continue
        if name.startswith("Binarized") and "kernel" in sub:
            total += int(jnp.asarray(sub["kernel"]).size) * 4
        else:
            total += _binarized_kernel_bytes(sub)
    return total


def _packed_bytes(frozen_blocks: list, embed_w=None) -> int:
    """Artifact weight bytes: int32 bitplanes for packed layers, fp32
    kernels for dense-carried ones (partial binarization)."""
    per_block = sum(
        int(jnp.asarray(b[key].get("wp", b[key].get("kernel"))).size) * 4
        for b in frozen_blocks
        for key in ("q", "k", "v", "out", "mlp1", "mlp2")
    )
    if embed_w is not None:
        per_block += int(jnp.asarray(embed_w).size) * 4
    return per_block


def _dense_carried_bytes(frozen_blocks: list) -> int:
    """fp32 bytes of dense-carried (unpacked) block kernels — identical
    in the live and frozen model, so added to BOTH sides of the
    compression ratio."""
    return sum(
        int(jnp.asarray(b[key]["kernel"]).size) * 4
        for b in frozen_blocks
        for key in ("q", "k", "v", "out", "mlp1", "mlp2")
        if "kernel" in b[key]
    )


def _freeze_info(params: Dict, blocks: list, kind: str,
                 embed_w=None) -> Dict[str, Any]:
    """The artifact's size-accounting dict, shared by both freezers."""
    # dense-carried fp32 kernels (partial binarization) weigh the same
    # live and frozen; count them on both sides so `compression` stays
    # the honest whole-model ratio
    latent = _binarized_kernel_bytes(params) + _dense_carried_bytes(blocks)
    packed = _packed_bytes(blocks, embed_w)
    return {
        "family": "bnn-transformer",
        "kind": kind,
        "latent_fp32_weight_bytes": latent,
        "frozen_weight_bytes": packed,
        "compression": round(latent / packed, 2),
        "packed_layers": [
            f"TransformerBlock_{i}.{k}"
            for i, b in enumerate(blocks)
            for k in ("q", "k", "v", "out", "mlp1", "mlp2")
            if "wp" in b[k]
        ],
    }


def _freeze_vit_tensors(
    model: BinarizedTransformer, variables: Dict
) -> Dict[str, Any]:
    _check_freezable(model)
    params = variables["params"]
    blocks = _freeze_blocks(params, model.depth, model.scale)
    # Patch embedding: binarized weights, raw-pixel input (first-layer
    # passthrough) — ±1 fp32 in memory, int8 on disk (export_packed).
    embed = params["BinarizedDense_0"]
    w_embed = binarize_ste(embed["kernel"])
    frozen: Dict[str, Any] = {
        "family": "bnn-transformer",
        "kind": "vit",
        "patch_size": model.patch_size,
        "num_heads": model.num_heads,
        "causal": False,
        "w_embed": w_embed,
        "b_embed": embed["bias"],
        "pos_embed": params["pos_embed"],
        "blocks": blocks,
        "ln_head": dict(params["ln_head"]),
        "head_w": params["head"]["kernel"],
        "head_b": params["head"]["bias"],
    }
    frozen["info"] = _freeze_info(params, blocks, "vit",
                                  embed_w=w_embed)
    return frozen


def _freeze_lm_tensors(model: BinarizedLM, variables: Dict) -> Dict[str, Any]:
    _check_freezable(model)
    params = variables["params"]
    blocks = _freeze_blocks(params, model.depth, model.scale)
    frozen: Dict[str, Any] = {
        "family": "bnn-transformer",
        "kind": "lm",
        "num_heads": model.num_heads,
        "causal": True,
        "tok_embed": params["tok_embed"]["embedding"],
        "pos_embed": params["pos_embed"],
        "blocks": blocks,
        "ln_head": dict(params["ln_head"]),
        "head_w": params["head"]["kernel"],
        "head_b": params["head"]["bias"],
    }
    frozen["info"] = _freeze_info(params, blocks, "lm")
    return frozen


def _block_layers(
    blk: Dict[str, Any], interpret: bool, fused: bool = False
) -> Dict[str, Callable]:
    """The per-block closures shared by the full forward (_block_fn) and
    the KV-cache decoder (_block_decode_fn) — one construction site so
    the two paths cannot drift. ``fused`` arms the fused unpack-GEMM
    carry of every packed projection (see :func:`_packed_dense_fn`)."""
    return {
        "ln_attn": _ln_fn(blk["ln_attn"]),
        "ln_mlp": _ln_fn(blk["ln_mlp"]),
        "q": _dense_fn(blk["q"], interpret, fused),
        "k": _dense_fn(blk["k"], interpret, fused),
        "v": _dense_fn(blk["v"], interpret, fused),
        "out": _dense_fn(blk["out"], interpret, fused),
        "mlp1": _dense_fn(blk["mlp1"], interpret, fused),
        "mlp2": _dense_fn(blk["mlp2"], interpret, fused),
    }


def _block_fn(blk: Dict[str, Any], num_heads: int, causal: bool,
              interpret: bool) -> Callable:
    lay = _block_layers(blk, interpret)
    ln_attn, ln_mlp = lay["ln_attn"], lay["ln_mlp"]
    q_fn, k_fn, v_fn, out_fn = lay["q"], lay["k"], lay["v"], lay["out"]
    mlp1, mlp2 = lay["mlp1"], lay["mlp2"]

    def fn(x: jnp.ndarray) -> jnp.ndarray:
        b, t, e = x.shape
        d = e // num_heads
        y = ln_attn(x)
        q = q_fn(y).reshape(b, t, num_heads, d)
        k = k_fn(y).reshape(b, t, num_heads, d)
        v = v_fn(y).reshape(b, t, num_heads, d)
        core = _attend_xla(q, k, v, causal=causal)
        x = x + out_fn(core.reshape(b, t, e))
        y = ln_mlp(x)
        y = nn.hard_tanh(mlp1(y))
        return x + mlp2(y)

    return fn


def _build_transformer_apply(
    frozen: Dict[str, Any], interpret: bool
) -> Callable:
    """Jittable frozen forward from a ``bnn-transformer`` artifact
    (in-memory or msgpack-restored)."""
    kind = frozen.get("kind", "vit")
    num_heads = int(frozen["num_heads"])
    causal = bool(frozen["causal"])
    blocks = [
        _block_fn(blk, num_heads, causal, interpret)
        for blk in frozen["blocks"]
    ]
    ln_head = _ln_fn(frozen["ln_head"])
    head_w = jnp.asarray(frozen["head_w"], jnp.float32)
    head_b = jnp.asarray(frozen["head_b"], jnp.float32)
    pos = jnp.asarray(frozen["pos_embed"], jnp.float32)

    if kind == "lm":
        tok = jnp.asarray(frozen["tok_embed"], jnp.float32)
        max_len = int(pos.shape[1])

        def apply_fn(tokens: jnp.ndarray) -> jnp.ndarray:
            t = tokens.shape[1]
            if t > max_len:  # static shape: raises at trace time, like
                raise ValueError(  # the live model (transformer.py:285)
                    f"sequence length {t} > max_len {max_len}"
                )
            x = tok[tokens] + pos[:, :t]
            for blk in blocks:
                x = blk(x)
            x = ln_head(x)
            return nn.log_softmax(x @ head_w + head_b)

        return jax.jit(apply_fn)

    patch = int(frozen["patch_size"])
    # NOTE: no alpha on the patch embedding — the live model never passes
    # ``scale`` to it (models/transformer.py:224-230), only to the
    # attention/MLP projections.
    w_embed = jnp.asarray(frozen["w_embed"], jnp.float32)  # disk: int8 ±1
    b_embed = jnp.asarray(frozen["b_embed"], jnp.float32)

    n_tokens = int(pos.shape[1])

    def apply_fn(images: jnp.ndarray) -> jnp.ndarray:
        b, h, w, c = images.shape
        # Static shapes: raise at trace time like the live model's
        # divisibility check (models/transformer.py) — without this, a
        # non-divisible or wrong-resolution input would silently truncate
        # border pixels and serve finite-but-wrong log-probs.
        if h % patch or w % patch:
            raise ValueError(
                f"input {h}x{w} not divisible by patch size {patch}"
            )
        nh, nw = h // patch, w // patch
        if nh * nw != n_tokens:
            raise ValueError(
                f"input {h}x{w} yields {nh * nw} patch tokens but the "
                f"artifact's pos_embed was trained for {n_tokens}"
            )
        x = images.reshape(b, nh, patch, nw, patch, c)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, nh * nw, -1)
        x = x.astype(jnp.float32) @ w_embed
        x = x + b_embed + pos
        for blk in blocks:
            x = blk(x)
        x = ln_head(x).mean(axis=1)
        return nn.log_softmax(x @ head_w + head_b)

    return jax.jit(apply_fn)


def freeze_bnn_vit(
    model: BinarizedTransformer, variables: Dict, *, interpret: bool = False
) -> Tuple[Callable, Dict[str, Any]]:
    """Freeze a trained binarized vit into packed inference; matches
    ``model.apply(variables, x, train=False)`` for attention="xla"
    models (see module docstring for the flash caveat)."""
    frozen = _freeze_vit_tensors(model, variables)
    return _build_transformer_apply(frozen, interpret), frozen["info"]


def freeze_bnn_lm(
    model: BinarizedLM, variables: Dict, *, interpret: bool = False
) -> Tuple[Callable, Dict[str, Any]]:
    """Freeze a trained BinarizedLM into packed next-token inference:
    ``fn(tokens) -> (B, T, vocab)`` log-probs, a drop-in predictor for
    autoregressive sampling (the --sample loop in examples/lm_demo.run)."""
    frozen = _freeze_lm_tensors(model, variables)
    return _build_transformer_apply(frozen, interpret), frozen["info"]


# ---------------------------------------------------------------------------
# KV-cache incremental decoding — the packed LM's serving loop
# ---------------------------------------------------------------------------

# Prompt positions per prefill dispatch (generate() and the serve/lm/
# engine default). One compiled (B, C) prefill program serves any prompt
# length: full chunks dispatch through it, the tail goes token-at-a-time.
PREFILL_CHUNK = 16


def _block_decode_fn(blk: Dict[str, Any], num_heads: int,
                     interpret: bool) -> Callable:
    """One block's single-position step against a (B, L, H, D) KV cache:
    ``fn(x (B, E), kc, vc, pos) -> (x, kc, vc)``. Positions > ``pos`` are
    masked out of the softmax (exp(-inf) = 0 exactly, so the zero-init
    cache tail never contributes)."""
    lay = _block_layers(blk, interpret)
    ln_attn, ln_mlp = lay["ln_attn"], lay["ln_mlp"]
    q_fn, k_fn, v_fn, out_fn = lay["q"], lay["k"], lay["v"], lay["out"]
    mlp1, mlp2 = lay["mlp1"], lay["mlp2"]

    def fn(x, kc, vc, pos):
        b, e = x.shape
        h = num_heads
        d = e // h
        y = ln_attn(x)
        q = q_fn(y).reshape(b, h, d)
        k = k_fn(y).reshape(b, 1, h, d)
        v = v_fn(y).reshape(b, 1, h, d)
        kc = jax.lax.dynamic_update_slice(kc, k, (0, pos, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, v, (0, pos, 0, 0))
        scale = d ** -0.5
        scores = jnp.einsum("bhd,blhd->bhl", q, kc) * scale
        l = kc.shape[1]
        mask = jnp.arange(l) <= pos                       # causal prefix
        scores = jnp.where(mask[None, None, :], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        core = jnp.einsum("bhl,blhd->bhd", probs, vc)
        x = x + out_fn(core.reshape(b, e))
        y = ln_mlp(x)
        y = nn.hard_tanh(mlp1(y))
        return x + mlp2(y), kc, vc

    return fn


def _block_chunk_fn(blk: Dict[str, Any], num_heads: int, cache_len: int,
                    interpret: bool) -> Callable:
    """One block's chunked-prefill step against a (B, L, H, D) KV cache:
    ``fn(x (B, C, E), kc, vc, start) -> (x, kc, vc)`` — C prompt
    positions written at [start, start+C) in one dispatch, attending
    causally (key pos <= query pos) over the whole cache strip. The
    per-position K/V values are identical to C single-position
    ``_block_decode_fn`` steps (projections are per-token), so chunked
    and token-at-a-time prefill build bitwise-identical caches."""
    lay = _block_layers(blk, interpret)
    ln_attn, ln_mlp = lay["ln_attn"], lay["ln_mlp"]
    q_fn, k_fn, v_fn, out_fn = lay["q"], lay["k"], lay["v"], lay["out"]
    mlp1, mlp2 = lay["mlp1"], lay["mlp2"]

    def fn(x, kc, vc, start):
        b, c, e = x.shape
        h = num_heads
        d = e // h
        y = ln_attn(x)
        q = q_fn(y).reshape(b, c, h, d)
        k = k_fn(y).reshape(b, c, h, d)
        v = v_fn(y).reshape(b, c, h, d)
        kc = jax.lax.dynamic_update_slice(kc, k, (0, start, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, v, (0, start, 0, 0))
        scale = d ** -0.5
        scores = jnp.einsum("bchd,blhd->bchl", q, kc) * scale
        qpos = start + jnp.arange(c)
        mask = jnp.arange(cache_len)[None, :] <= qpos[:, None]  # (C, L)
        scores = jnp.where(mask[None, :, None, :], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        core = jnp.einsum("bchl,blhd->bchd", probs, vc)
        x = x + out_fn(core.reshape(b, c, e))
        y = ln_mlp(x)
        y = nn.hard_tanh(mlp1(y))
        return x + mlp2(y), kc, vc

    return fn


def make_lm_decoder(
    frozen: Dict[str, Any], *, max_len: int | None = None,
    interpret: bool = False,
) -> Tuple[Callable, Callable]:
    """Incremental (KV-cached) decoding from a frozen ``kind == "lm"``
    artifact: each emitted token costs one single-position forward —
    O(T·L) attention over the cache instead of the full-window re-forward's
    O(T²·L) — and every projection GEMM has batch-1 rows, the
    bandwidth-bound regime where the pre-packed 1-bit weights read 32x
    less HBM than fp32 masters (PERF.md §3).

    Returns ``(init_caches, step)``:
      * ``init_caches(batch) -> caches`` — zeroed per-layer (B, L, H, D)
        K/V pairs, L = ``max_len or pos_embed length``.
      * ``step(caches, tokens (B,), pos) -> (caches, log_probs (B, vocab))``
        — jitted; feed prompt tokens one position at a time (teacher
        forcing), then sample from the returned next-token log-probs.
    """
    if frozen.get("kind") != "lm":
        raise ValueError(
            f"make_lm_decoder needs a kind='lm' artifact, got "
            f"{frozen.get('kind')!r}"
        )
    num_heads = int(frozen["num_heads"])
    tok = jnp.asarray(frozen["tok_embed"], jnp.float32)
    pos_embed = jnp.asarray(frozen["pos_embed"], jnp.float32)
    ln_head = _ln_fn(frozen["ln_head"])
    head_w = jnp.asarray(frozen["head_w"], jnp.float32)
    head_b = jnp.asarray(frozen["head_b"], jnp.float32)
    blocks = [
        _block_decode_fn(blk, num_heads, interpret)
        for blk in frozen["blocks"]
    ]
    embed_dim = int(tok.shape[1])
    pos_len = int(pos_embed.shape[1])
    cache_len = pos_len if max_len is None else int(max_len)
    if not 1 <= cache_len <= pos_len:
        raise ValueError(
            f"max_len {cache_len} outside [1, trained pos_embed length "
            f"{pos_len}]"
        )
    head_dim = embed_dim // num_heads

    def init_caches(batch: int):
        shape = (batch, cache_len, num_heads, head_dim)
        return tuple(
            (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32))
            for _ in blocks
        )

    def _step(caches, tokens, pos):
        x = tok[tokens] + pos_embed[0, pos]
        new = []
        for blk, (kc, vc) in zip(blocks, caches):
            x, kc, vc = blk(x, kc, vc, pos)
            new.append((kc, vc))
        x = ln_head(x)
        return tuple(new), nn.log_softmax(x @ head_w + head_b)

    jitted = jax.jit(_step)

    def step(caches, tokens, pos):
        # Host-int bounds check ONLY (a plain integer compare): under
        # jit, an out-of-range pos would silently clamp both the cache
        # write and the pos-embed lookup (XLA dynamic_update_slice
        # semantics) and return finite-but-wrong log-probs. The old
        # ``int(pos)`` guard forced a device->host sync per token when
        # pos arrived as a device scalar — the decode hot loop must stay
        # trace-pure, so device/traced positions skip the check and are
        # the caller's contract: validate total length upfront at
        # init/prefill time (generate() does; the paged engine sizes
        # page tables at admission).
        if isinstance(pos, (int, np.integer)) and pos >= cache_len:
            raise ValueError(
                f"decode position {int(pos)} >= cache length {cache_len}"
            )
        return jitted(caches, tokens, pos)

    # -- chunked prefill: C prompt positions per dispatch ---------------
    chunk_blocks = [
        _block_chunk_fn(blk, num_heads, cache_len, interpret)
        for blk in frozen["blocks"]
    ]

    def _prefill(caches, tokens, start):
        """(B, C) prompt chunk written at [start, start+C) — caller
        guarantees start + C <= cache_len (generate() only dispatches
        full chunks), so the dynamic_update_slice never clamps."""
        c = tokens.shape[1]
        qpos = start + jnp.arange(c)
        x = tok[tokens] + pos_embed[0][jnp.clip(qpos, 0, pos_len - 1)]
        new = []
        for blk, (kc, vc) in zip(chunk_blocks, caches):
            x, kc, vc = blk(x, kc, vc, start)
            new.append((kc, vc))
        x = ln_head(x)
        return tuple(new), nn.log_softmax(x @ head_w + head_b)

    # Expose the cache length so callers holding only the (init, step)
    # pair — e.g. generate(decoder=...) — can validate total sequence
    # length upfront instead of failing mid-decode after paid prefill.
    init_caches.cache_len = cache_len
    step.cache_len = cache_len
    step.prefill = jax.jit(_prefill)
    return init_caches, step


def generate(
    frozen: Dict[str, Any], prompt, n_tokens: int, *,
    temperature: float = 0.0, rng=None, interpret: bool = False,
    decoder: Tuple[Callable, Callable] | None = None,
) -> jnp.ndarray:
    """Autoregressive generation from a frozen LM artifact via the
    KV-cache decoder: feed the prompt one position at a time (teacher
    forcing), then sample ``n_tokens`` continuations — greedy at
    ``temperature=0``, else categorical with ``rng``.

    ``prompt``: (B, P) int tokens. Returns (B, P + n_tokens). The serving
    loop is host-driven (one jitted single-position step per token), so
    total length must fit the artifact's trained ``max_len``.

    A serving loop calling this per request should build the decoder once
    and pass it as ``decoder=make_lm_decoder(frozen)`` — otherwise every
    call constructs fresh jitted closures and repays XLA compilation,
    which dominates single-position decode cost.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt.ndim != 2 or prompt.shape[1] < 1:
        raise ValueError(f"prompt must be (B, P>=1), got {prompt.shape}")
    if n_tokens < 0:
        raise ValueError(f"n_tokens must be >= 0, got {n_tokens}")
    if temperature < 0:
        raise ValueError(
            f"temperature must be >= 0 (0 = greedy), got {temperature}"
        )
    total = prompt.shape[1] + n_tokens
    cache_len = int(jnp.asarray(frozen["pos_embed"]).shape[1])
    if total > cache_len:
        raise ValueError(
            f"prompt {prompt.shape[1]} + n_tokens {n_tokens} = {total} "
            f"exceeds the artifact's trained max_len {cache_len}"
        )
    if decoder is None:
        # Rebuilding the decoder means fresh jitted closures and a full
        # XLA re-compile PER CALL — fine for a one-shot CLI sample,
        # a serving disaster (compile time dwarfs single-position decode
        # cost). The one-decoder-per-artifact rule (SERVING.md): build
        # ``make_lm_decoder(frozen)`` once and pass it as ``decoder=``.
        # The serve/lm/ engine never takes this path; the counter + log
        # make any accidental hot-path rebuild visible in telemetry.
        from .obs import default_registry as _default_registry

        _default_registry().counter(
            "lm_decoder_rebuilds_total",
            "generate() calls that rebuilt the jitted LM decoder "
            "(pass decoder=make_lm_decoder(frozen) on hot paths)",
        ).inc()
        log.warning(
            "generate() called without a prebuilt decoder: rebuilding "
            "jitted closures (full XLA re-compile). Serving loops must "
            "build make_lm_decoder(frozen) once per artifact and pass "
            "decoder= (SERVING.md, one-decoder-per-artifact rule)."
        )
    init, step = decoder or make_lm_decoder(frozen, interpret=interpret)
    # A caller-supplied decoder may have been built with max_len < the
    # artifact's trained length; validate against its actual cache before
    # spending prefill compute (step() would only fail mid-decode).
    dec_len = getattr(step, "cache_len", None)
    if dec_len is not None and total > dec_len:
        raise ValueError(
            f"prompt {prompt.shape[1]} + n_tokens {n_tokens} = {total} "
            f"exceeds the supplied decoder's cache length {dec_len}"
        )
    caches = init(prompt.shape[0])
    if temperature > 0 and rng is None:
        raise ValueError("temperature > 0 needs an rng key")

    # Serving telemetry (obs/): token counters + per-token decode
    # latency, so a `telemetry` snapshot shows decode throughput next to
    # training throughput. Host-observed wall time over the whole decode
    # loop, amortized per emitted token.
    from .obs import default_registry

    _reg = default_registry()
    _reg.counter(
        "lm_prefill_tokens_total", "prompt tokens fed through prefill"
    ).inc(int(prompt.shape[0]) * int(prompt.shape[1]))

    # Chunked prefill: feed the prompt in fixed-width (B, C) chunks —
    # one dispatch per C positions instead of C single-position steps —
    # falling back to token-at-a-time for the sub-chunk tail (and for
    # caller-supplied decoders built before prefill existed). Cache
    # contents are bitwise-identical either way (_block_chunk_fn).
    lp = None
    prefill = getattr(step, "prefill", None)
    chunk = PREFILL_CHUNK
    t = 0
    if prefill is not None:
        n_prompt = prompt.shape[1]
        while t + chunk <= n_prompt:
            caches, clp = prefill(
                caches, prompt[:, t:t + chunk], jnp.int32(t)
            )
            lp = clp[:, -1]
            t += chunk
    for t in range(t, prompt.shape[1]):        # sub-chunk tail
        caches, lp = step(caches, prompt[:, t], t)
    out = [prompt]
    if n_tokens > 0 and lp is not None:
        # Sync the (async-dispatched) prefill before starting the decode
        # clock, or the per-token metric silently absorbs the prompt's
        # device time.
        jax.block_until_ready(lp)
    _t0 = time.perf_counter()
    for t in range(prompt.shape[1], total):    # decode
        if temperature > 0:
            rng, sub = jax.random.split(rng)
            nxt = jax.random.categorical(sub, lp / temperature, axis=-1)
        else:
            nxt = jnp.argmax(lp, axis=-1)
        nxt = nxt.astype(jnp.int32)
        out.append(nxt[:, None])
        if t < total - 1:
            caches, lp = step(caches, nxt, t)
    result = jnp.concatenate(out, axis=1)
    if n_tokens > 0:
        jax.block_until_ready(result)
        _reg.counter(
            "lm_decode_tokens_total", "tokens emitted by KV-cache decode"
        ).inc(int(prompt.shape[0]) * n_tokens)
        _reg.histogram(
            "lm_decode_seconds_per_token",
            "KV-cache decode wall time per emitted token",
        ).observe((time.perf_counter() - _t0) / n_tokens)
    return result


# ---------------------------------------------------------------------------
# Paged KV-cache decoding — continuous batching (serve/lm/)
# ---------------------------------------------------------------------------


class PagedLMDecoder(NamedTuple):
    """The compiled programs behind the continuous-batching engine
    (SERVING.md "Continuous LM serving") plus their fixed geometry.

    Exactly TWO programs exist after warmup — THREE when speculative
    decoding is armed (``spec_k > 0``) — and every dynamic quantity
    (tokens, page tables, positions, chunk start/length) is an array
    argument, so the engine admits/evicts sequences at any iteration
    with zero recompiles:

      * ``prefill(pools, tokens (C,), page_table (P,), start, length)``
        -> ``(pools, log_probs (C, vocab))`` — one sequence's prompt
        chunk: K/V written through the page table (padding positions
        >= ``length`` are redirected to the null page), causal
        attention over the table, per-position next-token log-probs.
      * ``decode(pools, tokens (S,), page_tables (S, P), positions
        (S,))`` -> ``(pools, log_probs (S, vocab))`` — one iteration
        for all S batch slots at once; inactive slots carry all-null
        tables and are garbage-out/ignored.
      * ``verify(pools, tokens (S, K), page_tables (S, P), positions
        (S,))`` -> ``(pools, log_probs (S, K, vocab))`` — the
        speculative-decode scorer: K consecutive input tokens per slot
        starting at each slot's base position, K/V written (overwriting
        the draft's packed-weight writes with the verifier's canonical
        values) and causal log-probs returned for every position, in
        ONE large-M dispatch on the **dense bf16** carry of the same
        weights (PERF.md §3 crossover — see :func:`_dense_bf16_fn`).
        ``K = spec_k`` is fixed at build time: the compiled signature
        never depends on how many drafts a round accepts (accept/
        reject is host-side), which is what keeps the budget-0
        recompile fence green with spec decode armed.

    All are jitted with the pools donated (``donate``): the KV pool is
    the engine's dominant buffer and must be updated in place, not
    copied per token. Callers therefore must NOT reuse a pools value
    after passing it in — hold only the returned pools.
    """

    init_pools: Callable
    prefill: Callable
    decode: Callable
    slots: int
    page_size: int
    num_pages: int
    max_pages: int          # page-table width (pages per sequence)
    max_len: int            # longest sequence (prompt + generated)
    prefill_chunk: int
    vocab: int
    num_blocks: int
    verify: Optional[Callable] = None   # spec-decode scorer (or None)
    spec_k: int = 0         # verify window width (0 = spec decode off)
    kernels: bool = False   # Pallas paged-attention + fused-unpack path


def make_paged_lm_decoder(
    frozen: Dict[str, Any], *,
    slots: int,
    page_size: int = 16,
    num_pages: int | None = None,
    prefill_chunk: int = PREFILL_CHUNK,
    max_len: int | None = None,
    interpret: bool = False,
    donate: bool = True,
    spec_k: int = 0,
    kernels: bool = False,
) -> PagedLMDecoder:
    """Build the paged prefill/decode pair from a ``kind == "lm"``
    artifact (see :class:`PagedLMDecoder`). ``num_pages`` defaults to
    enough for every slot to reach ``max_len`` simultaneously, plus the
    reserved null page — callers running oversubscribed (more admitted
    work than worst-case pages) size it down and rely on the engine's
    admission control.

    ``spec_k > 0`` additionally compiles the fixed-K ``verify``
    program (self-speculative decoding, SERVING.md): the engine drafts
    ``spec_k - 1`` tokens through the packed ``decode`` program and
    scores the whole window — the pending token plus the drafts — in
    one dense-bf16 dispatch. ``spec_k == 1`` degenerates to a
    one-token-per-round bf16 verifier with no drafts (the
    "verifier-alone" reference engine of the equivalence suite).

    ``kernels=True`` arms the Pallas serving path: paged attention runs
    the in-kernel page-table walk (``paged_kv.paged_attention_kernel``
    and its prefill/verify twins — no materialized K/V gather) and every
    packed projection runs the fused unpack-GEMM
    (``xnor_matmul_fused_unpack`` — bitplanes expand in VMEM, HBM
    weight traffic stays 1/32 byte/param). The gather + popcount path
    (``kernels=False``) is kept as the correctness oracle; greedy
    output is token-identical between the two (the fused GEMM is
    bitwise-equal on ±1, attention matches to fp tolerance)."""
    from .ops import paged_kv

    if frozen.get("kind") != "lm":
        raise ValueError(
            f"make_paged_lm_decoder needs a kind='lm' artifact, got "
            f"{frozen.get('kind')!r}"
        )
    num_heads = int(frozen["num_heads"])
    tok = jnp.asarray(frozen["tok_embed"], jnp.float32)
    pos_embed = jnp.asarray(frozen["pos_embed"], jnp.float32)
    ln_head = _ln_fn(frozen["ln_head"])
    head_w = jnp.asarray(frozen["head_w"], jnp.float32)
    head_b = jnp.asarray(frozen["head_b"], jnp.float32)
    kernels = bool(kernels)
    layers = [
        _block_layers(blk, interpret, fused=kernels)
        for blk in frozen["blocks"]
    ]
    if kernels:
        _attn = functools.partial(
            paged_kv.paged_attention_kernel, interpret=interpret
        )
        _attn_prefill = functools.partial(
            paged_kv.paged_prefill_attention_kernel, interpret=interpret
        )
        _attn_verify = functools.partial(
            paged_kv.paged_verify_attention_kernel, interpret=interpret
        )
    else:
        _attn = paged_kv.paged_attention
        _attn_prefill = paged_kv.paged_prefill_attention
        _attn_verify = paged_kv.paged_verify_attention
    embed_dim = int(tok.shape[1])
    head_dim = embed_dim // num_heads
    pos_len = int(pos_embed.shape[1])
    max_len = pos_len if max_len is None else int(max_len)
    if not 1 <= max_len <= pos_len:
        raise ValueError(
            f"max_len {max_len} outside [1, trained pos_embed length "
            f"{pos_len}]"
        )
    slots = int(slots)
    if slots < 1:
        raise ValueError(f"need >= 1 batch slot, got {slots}")
    page_size = int(page_size)
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    prefill_chunk = int(prefill_chunk)
    if prefill_chunk < 1:
        raise ValueError(
            f"prefill_chunk must be >= 1, got {prefill_chunk}"
        )
    spec_k = int(spec_k)
    if spec_k < 0:
        raise ValueError(f"spec_k must be >= 0, got {spec_k}")
    max_pages = paged_kv.pages_needed(max_len, page_size)
    if num_pages is None:
        num_pages = slots * max_pages + 1        # +1: the null page
    num_pages = int(num_pages)
    n_blocks = len(layers)

    def init_pools():
        return paged_kv.init_pools(
            n_blocks, num_pages, page_size, num_heads, head_dim
        )

    def _mlp(lay, x):
        return x + lay["mlp2"](nn.hard_tanh(lay["mlp1"](lay["ln_mlp"](x))))

    def _head(x):
        return nn.log_softmax(ln_head(x) @ head_w + head_b)

    def _prefill(pools, tokens, page_table, start, length):
        c = tokens.shape[0]
        gpos = start + jnp.arange(c)
        valid = gpos < length
        x = tok[tokens] + pos_embed[0][jnp.clip(gpos, 0, pos_len - 1)]
        idx = paged_kv.flat_write_indices(
            page_table, gpos, page_size, valid=valid
        )
        new = []
        for lay, (kp, vp) in zip(layers, pools):
            y = lay["ln_attn"](x)
            q = lay["q"](y).reshape(c, num_heads, head_dim)
            k = lay["k"](y).reshape(c, num_heads, head_dim)
            v = lay["v"](y).reshape(c, num_heads, head_dim)
            kp = paged_kv.write_kv(kp, idx, k)
            vp = paged_kv.write_kv(vp, idx, v)
            core = _attn_prefill(q, kp, vp, page_table, gpos)
            x = x + lay["out"](core.reshape(c, embed_dim))
            x = _mlp(lay, x)
            new.append((kp, vp))
        return tuple(new), _head(x)

    def _decode(pools, tokens, page_tables, positions):
        s = tokens.shape[0]
        x = tok[tokens] + pos_embed[0][jnp.clip(positions, 0, pos_len - 1)]
        idx = paged_kv.flat_write_indices(
            page_tables, positions, page_size
        )
        new = []
        for lay, (kp, vp) in zip(layers, pools):
            y = lay["ln_attn"](x)
            q = lay["q"](y).reshape(s, num_heads, head_dim)
            k = lay["k"](y).reshape(s, num_heads, head_dim)
            v = lay["v"](y).reshape(s, num_heads, head_dim)
            kp = paged_kv.write_kv(kp, idx, k)
            vp = paged_kv.write_kv(vp, idx, v)
            core = _attn(q, kp, vp, page_tables, positions)
            x = x + lay["out"](core.reshape(s, embed_dim))
            x = _mlp(lay, x)
            new.append((kp, vp))
        return tuple(new), _head(x)

    donate_kw = {"donate_argnums": (0,)} if donate else {}

    verify_fn = None
    if spec_k:
        # The verifier carry of the SAME weights: dense ±1 bf16 kernels
        # (exact-equal GEMM math to the packed path, _dense_bf16_fn) —
        # the large-M format for the one batched dispatch that scores
        # the whole K-token window.
        vlayers = [
            {
                "ln_attn": _ln_fn(blk["ln_attn"]),
                "ln_mlp": _ln_fn(blk["ln_mlp"]),
                "q": _dense_bf16_fn(blk["q"]),
                "k": _dense_bf16_fn(blk["k"]),
                "v": _dense_bf16_fn(blk["v"]),
                "out": _dense_bf16_fn(blk["out"]),
                "mlp1": _dense_bf16_fn(blk["mlp1"]),
                "mlp2": _dense_bf16_fn(blk["mlp2"]),
            }
            for blk in frozen["blocks"]
        ]

        def _verify(pools, tokens, page_tables, positions):
            s, k = tokens.shape
            qpos = positions[:, None] + jnp.arange(k)[None, :]  # (S, K)
            x = tok[tokens] + pos_embed[0][jnp.clip(qpos, 0, pos_len - 1)]
            tables_k = jnp.broadcast_to(
                page_tables[:, None, :],
                (s, k, page_tables.shape[-1]),
            )
            idx = paged_kv.flat_write_indices(tables_k, qpos, page_size)
            new = []
            for lay, (kp, vp) in zip(vlayers, pools):
                y = lay["ln_attn"](x)
                q = lay["q"](y).reshape(s, k, num_heads, head_dim)
                kk = lay["k"](y).reshape(s, k, num_heads, head_dim)
                v = lay["v"](y).reshape(s, k, num_heads, head_dim)
                # Overwrites the draft's packed-weight K/V for the
                # window with the verifier's canonical values — the
                # accepted prefix's cache state is the verifier's, so
                # later rounds (and published prefix pages) attend to
                # verifier-grade history.
                kp = paged_kv.write_kv(kp, idx, kk)
                vp = paged_kv.write_kv(vp, idx, v)
                core = _attn_verify(q, kp, vp, page_tables, positions)
                x = x + lay["out"](core.reshape(s, k, embed_dim))
                x = _mlp(lay, x)
                new.append((kp, vp))
            return tuple(new), _head(x)

        verify_fn = jax.jit(_verify, **donate_kw)

    return PagedLMDecoder(
        init_pools=init_pools,
        prefill=jax.jit(_prefill, **donate_kw),
        decode=jax.jit(_decode, **donate_kw),
        slots=slots,
        page_size=page_size,
        num_pages=num_pages,
        max_pages=max_pages,
        max_len=max_len,
        prefill_chunk=prefill_chunk,
        vocab=int(tok.shape[0]),
        num_blocks=n_blocks,
        verify=verify_fn,
        spec_k=spec_k,
        kernels=kernels,
    )
