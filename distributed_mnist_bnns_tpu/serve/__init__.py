"""serve — resilient long-running packed-inference serving.

The deployment half the one-shot ``cli infer`` evaluator lacks: a
long-running HTTP server over ``infer.load_packed`` artifacts with the
Tail-at-Scale failure modes engineered in, not hoped away:

  core.py    requests with deadlines, the bounded admission queue with
             load shedding, and the micro-batching engine that pads
             every dispatch to the ONE compiled batch shape
  server.py  the stdlib HTTP front end (/predict, /healthz, /metrics,
             /admin/reload), hot artifact swap, and the SIGTERM
             graceful drain (stop admitting → flush → exit 0)
  client.py  tiny urllib client used by tests and the CI smoke
  lm/        continuous-batching LM serving: iteration-level scheduler
             over a paged KV cache, streaming `/generate` endpoint
             (``cli serve --lm``; import ``serve.lm`` explicitly — it
             pulls the jax-heavy decoder, this package root stays light)
  fleet/     multi-replica serving fleet (``cli fleet``): deadline-aware
             least-loaded router with per-replica health + breakers and
             prefix-affinity, replica supervisor with autoscaling off
             sustained queue depth/shed rate, rolling deploys with
             canary gates + automatic rollback (SERVING.md "Fleet";
             import ``serve.fleet`` explicitly)

The circuit breaker lives in ``resilience.policy.CircuitBreaker`` (so
training restart loops can reuse it); serving chaos (``infer_slow`` /
``infer_error``) in ``resilience.chaos``. See SERVING.md "Live
serving", RESILIENCE.md for the fault kinds, OBSERVABILITY.md for the
``request`` / ``shed`` / ``breaker_open`` / ``breaker_close`` /
``drain`` / ``reload`` event schema.
"""

from .core import DEFAULT_TIER, TIERS, AdmissionQueue, Request, ServeEngine
from .server import PackedInferenceServer, ServeConfig

__all__ = [
    "AdmissionQueue",
    "DEFAULT_TIER",
    "PackedInferenceServer",
    "Request",
    "ServeConfig",
    "ServeEngine",
    "TIERS",
]
