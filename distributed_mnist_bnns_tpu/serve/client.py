"""Minimal stdlib HTTP client for the packed-inference server.

Shared by tests/test_serve.py and scripts/serve_smoke.py so both speak
the exact wire protocol the server implements (and the smoke stays
dependency-free). Every helper returns ``(status_code, body_bytes)`` —
raw bytes on purpose: the hot-reload acceptance check compares response
bodies bitwise across an artifact swap.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, Optional, Tuple

from ..obs.trace import TRACE_HEADER, format_header, mint_context


def _request(
    url: str, *, data: Optional[bytes] = None, timeout: float = 30.0,
    headers: Optional[Dict[str, str]] = None,
) -> Tuple[int, bytes]:
    req = urllib.request.Request(
        url, data=data,
        headers={
            **({"Content-Type": "application/json"} if data else {}),
            **(headers or {}),
        },
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        # 4xx/5xx still carry the server's JSON body — that's the shed/
        # deadline/breaker signal callers assert on, not a client crash.
        return e.code, e.read()


def predict(
    base_url: str, images: Any, *,
    deadline_ms: Optional[float] = None, timeout: float = 30.0,
    trace: Any = None,
) -> Tuple[int, bytes]:
    """POST /predict. ``trace``: the x-jg-trace contract's client half —
    ``True`` mints a fresh context, or pass a ``TraceContext`` /
    preformatted header string; the server adopts it and roots the
    request's span tree under it."""
    body: Dict[str, Any] = {"images": images}
    if deadline_ms is not None:
        body["deadline_ms"] = deadline_ms
    headers = None
    if trace is not None:
        if trace is True:
            trace = mint_context()
        value = trace if isinstance(trace, str) else format_header(trace)
        headers = {TRACE_HEADER: value}
    return _request(
        base_url + "/predict", data=json.dumps(body).encode(),
        timeout=timeout, headers=headers,
    )


def healthz(base_url: str, timeout: float = 10.0) -> Tuple[int, bytes]:
    return _request(base_url + "/healthz", timeout=timeout)


def metrics(base_url: str, timeout: float = 10.0) -> Tuple[int, bytes]:
    return _request(base_url + "/metrics", timeout=timeout)


def reload_artifact(
    base_url: str, artifact: Optional[str] = None, timeout: float = 60.0
) -> Tuple[int, bytes]:
    body = {"artifact": artifact} if artifact else {}
    return _request(
        base_url + "/admin/reload", data=json.dumps(body).encode(),
        timeout=timeout,
    )
