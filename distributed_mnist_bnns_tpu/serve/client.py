"""Minimal stdlib HTTP client for the packed-inference server.

Shared by tests/test_serve.py and scripts/serve_smoke.py so both speak
the exact wire protocol the server implements (and the smoke stays
dependency-free). Every helper returns ``(status_code, body_bytes)`` —
raw bytes on purpose: the hot-reload acceptance check compares response
bodies bitwise across an artifact swap.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, Optional, Tuple


def _request(
    url: str, *, data: Optional[bytes] = None, timeout: float = 30.0
) -> Tuple[int, bytes]:
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        # 4xx/5xx still carry the server's JSON body — that's the shed/
        # deadline/breaker signal callers assert on, not a client crash.
        return e.code, e.read()


def predict(
    base_url: str, images: Any, *,
    deadline_ms: Optional[float] = None, timeout: float = 30.0,
) -> Tuple[int, bytes]:
    body: Dict[str, Any] = {"images": images}
    if deadline_ms is not None:
        body["deadline_ms"] = deadline_ms
    return _request(
        base_url + "/predict", data=json.dumps(body).encode(),
        timeout=timeout,
    )


def healthz(base_url: str, timeout: float = 10.0) -> Tuple[int, bytes]:
    return _request(base_url + "/healthz", timeout=timeout)


def metrics(base_url: str, timeout: float = 10.0) -> Tuple[int, bytes]:
    return _request(base_url + "/metrics", timeout=timeout)


def reload_artifact(
    base_url: str, artifact: Optional[str] = None, timeout: float = 60.0
) -> Tuple[int, bytes]:
    body = {"artifact": artifact} if artifact else {}
    return _request(
        base_url + "/admin/reload", data=json.dumps(body).encode(),
        timeout=timeout,
    )
