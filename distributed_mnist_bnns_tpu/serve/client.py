"""Minimal stdlib HTTP client for the packed-inference server.

Shared by tests/test_serve.py and scripts/serve_smoke.py so both speak
the exact wire protocol the server implements (and the smoke stays
dependency-free). Every helper returns ``(status_code, body_bytes)`` —
raw bytes on purpose: the hot-reload acceptance check compares response
bodies bitwise across an artifact swap.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Optional, Tuple

from ..obs.trace import TRACE_HEADER, format_header, mint_context


def parse_retry_after(value: Optional[str]) -> Optional[float]:
    """The servers send fractional delta-seconds (serve/server.py);
    a strict integer or garbage degrades gracefully."""
    if not value:
        return None
    try:
        after = float(value)
    except ValueError:
        return None
    return after if after >= 0 else None


def _request_full(
    url: str, *, data: Optional[bytes] = None, timeout: float = 30.0,
    headers: Optional[Dict[str, str]] = None,
) -> Tuple[int, bytes, Dict[str, str]]:
    req = urllib.request.Request(
        url, data=data,
        headers={
            **({"Content-Type": "application/json"} if data else {}),
            **(headers or {}),
        },
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        # 4xx/5xx still carry the server's JSON body — that's the shed/
        # deadline/breaker signal callers assert on, not a client crash.
        return e.code, e.read(), dict(e.headers or {})


def _request(
    url: str, *, data: Optional[bytes] = None, timeout: float = 30.0,
    headers: Optional[Dict[str, str]] = None,
) -> Tuple[int, bytes]:
    status, body, _ = _request_full(
        url, data=data, timeout=timeout, headers=headers
    )
    return status, body


def predict(
    base_url: str, images: Any, *,
    deadline_ms: Optional[float] = None, timeout: float = 30.0,
    trace: Any = None, tier: Optional[str] = None,
) -> Tuple[int, bytes]:
    """POST /predict. ``trace``: the x-jg-trace contract's client half —
    ``True`` mints a fresh context, or pass a ``TraceContext`` /
    preformatted header string; the server adopts it and roots the
    request's span tree under it. ``tier``: the SLO class (``interactive``
    / ``batch``; server default interactive)."""
    body: Dict[str, Any] = {"images": images}
    if deadline_ms is not None:
        body["deadline_ms"] = deadline_ms
    if tier is not None:
        body["tier"] = tier
    headers = None
    if trace is not None:
        if trace is True:
            trace = mint_context()
        value = trace if isinstance(trace, str) else format_header(trace)
        headers = {TRACE_HEADER: value}
    return _request(
        base_url + "/predict", data=json.dumps(body).encode(),
        timeout=timeout, headers=headers,
    )


def predict_with_retries(
    base_url: str, images: Any, *,
    deadline_ms: float = 2000.0,
    max_attempts: int = 6,
    backoff_s: float = 0.05,
    timeout: float = 30.0,
    trace: Any = None,
    tier: Optional[str] = None,
    seed: Optional[int] = 0,
    sleep=time.sleep,
) -> Tuple[int, bytes]:
    """``predict`` with retry-on-503/502 inside ONE overall deadline —
    the client half a router target expects (SERVING.md "Fleet").

    A 503 shed waits the server's ``Retry-After`` hint (capped by the
    remaining budget); 502s and transport errors back off jittered
    exponentially (:class:`~..resilience.policy.RetryPolicy`); 200 and
    4xx return immediately; a 504 means the budget died server-side, so
    there is nothing left to retry with. The per-attempt body carries
    the REMAINING deadline, never the original — a retry must not
    promise time it no longer has."""
    from ..resilience.policy import RetryPolicy

    policy = RetryPolicy(
        base_backoff_s=backoff_s, max_backoff_s=1.0, seed=seed
    )
    overall = time.monotonic() + deadline_ms / 1e3
    last: Tuple[int, bytes] = (599, b'{"error": "no attempt made"}')
    for attempt in range(1, max_attempts + 1):
        remaining_ms = (overall - time.monotonic()) * 1e3
        if remaining_ms <= 0:
            return last
        body: Dict[str, Any] = {
            "images": images, "deadline_ms": remaining_ms,
        }
        if tier is not None:
            body["tier"] = tier
        headers = None
        if trace is not None:
            if trace is True:
                trace = mint_context()
            value = (trace if isinstance(trace, str)
                     else format_header(trace))
            headers = {TRACE_HEADER: value}
        try:
            status, payload, rheaders = _request_full(
                base_url + "/predict", data=json.dumps(body).encode(),
                timeout=min(timeout, remaining_ms / 1e3 + 1.0),
                headers=headers,
            )
        except OSError as e:
            status, payload, rheaders = (
                -1, f'{{"error": "{type(e).__name__}"}}'.encode(), {}
            )
        last = (status, payload)
        if status == 200 or (400 <= status < 500) or status == 504:
            return last
        if attempt >= max_attempts:
            return last          # decided: don't sleep a dead delay
        if status == 503:
            delay = parse_retry_after(rheaders.get("Retry-After"))
            if delay is None:
                delay = policy.backoff(attempt)
        else:   # 5xx / transport error
            delay = policy.backoff(attempt)
        sleep(min(delay, max(overall - time.monotonic(), 0.0)))
    return last


def healthz(base_url: str, timeout: float = 10.0) -> Tuple[int, bytes]:
    return _request(base_url + "/healthz", timeout=timeout)


def metrics(base_url: str, timeout: float = 10.0) -> Tuple[int, bytes]:
    return _request(base_url + "/metrics", timeout=timeout)


def reload_artifact(
    base_url: str, artifact: Optional[str] = None, timeout: float = 60.0
) -> Tuple[int, bytes]:
    body = {"artifact": artifact} if artifact else {}
    return _request(
        base_url + "/admin/reload", data=json.dumps(body).encode(),
        timeout=timeout,
    )
