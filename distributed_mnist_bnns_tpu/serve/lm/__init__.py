"""serve.lm — continuous-batching LM serving (SERVING.md "Continuous
LM serving").

The generation counterpart of the packed classifier server: requests
join and leave ONE compiled decode batch at any iteration (Orca-style
iteration-level scheduling), KV memory is block-paged and freed the
moment a stream ends (PagedAttention-style page tables,
ops/paged_kv.py), tokens stream to clients incrementally over chunked
HTTP, and decode GEMMs run on the artifact's pre-packed 1-bit
bitplanes — the bandwidth-bound regime the packed kernel wins
(PERF.md §3).

  engine.py   LMEngine: bounded admission, iteration-level scheduler,
              chunked prefill at admission, page lifecycle, deadlines,
              recompile fence armed at budget 0; optional COW prefix
              caching and self-speculative decode rounds
  prefix_cache.py  radix index of page-size token blocks over the
              refcounted page pool (SERVING.md "Prefix caching")
  server.py   LMServer: POST /generate (ndjson over chunked HTTP),
              /healthz, /metrics, SIGTERM graceful drain
  client.py   stdlib streaming client (tests + CI smoke)

The compiled prefill/decode/verify programs themselves live in
``infer_transformer.make_paged_lm_decoder``; the page primitives in
``ops.paged_kv``.
"""

from .engine import LMEngine, LMRequest
from .prefix_cache import PrefixCache
from .server import LMServeConfig, LMServer

__all__ = [
    "LMEngine", "LMRequest", "LMServeConfig", "LMServer", "PrefixCache",
]
