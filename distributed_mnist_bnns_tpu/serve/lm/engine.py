"""Continuous-batching LM engine — iteration-level scheduling over a
paged KV cache (SERVING.md "Continuous LM serving").

The classifier engine (serve/core.py) batches whole requests: admit,
compute once, resolve. Generation is different — a request is a
*sequence* of decode iterations, and naive request-level batching leaves
slots idle while the longest sequence finishes. This engine schedules at
iteration granularity (Orca, OSDI '22): every decode step runs ALL
active batch slots through ONE jitted program, and between steps the
scheduler admits queued requests into freed slots, so sequences join and
leave the batch mid-generation with **zero post-warmup recompiles** —
every dynamic quantity (tokens, positions, page tables) is an array
argument of the single compiled decode signature.

KV memory is block-paged (ops/paged_kv.py, PagedAttention-style): a
request's cache lives in fixed-size pages allocated at admission and
returned to the free list the moment the request finishes, errors,
cancels or blows its deadline — page lifetime is request lifetime, not
slot lifetime, so a 504 frees its memory immediately.

Admission mirrors serve/core.py's Tail-at-Scale discipline: a bounded
queue (shed ``queue_full`` past it), per-request deadlines enforced both
while queued (never prefilled) and mid-stream (evicted between
iterations), and drain semantics (stop admitting, finish what's
streaming). Decode GEMMs run on the artifact's pre-packed 1-bit
bitplanes — single-position decode is exactly the bandwidth-bound
small-M regime the packed VPU kernel wins (PERF.md §3).

The recompile fence (analysis/guards.py) is armed with **budget 0**
after warmup: any post-warmup XLA compile is a bug (a shape or
weak-type leak into the hot loop) and hard-fails the engine rather than
shipping as silent per-token compile stalls.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from ...analysis.guards import (
    RecompileFenceError,
    Sanitizer,
    SanitizerConfig,
)
from ...obs.costs import get_ledger
from ...obs.profile import STEP_MARKER, get_profiler
from ...obs.trace import (
    NULL_SPAN,
    NULL_TRACER,
    TraceContext,
    next_request_id,
)
from ...ops.paged_kv import PageAllocator, pages_needed
from .prefix_cache import PrefixCache

log = logging.getLogger(__name__)

TOKENS_TOTAL = "lm_tokens_total"
PAGE_OCCUPANCY = "lm_page_occupancy"
ACTIVE_STREAMS = "lm_active_streams"
PREFILL_MS = "lm_prefill_ms"
DECODE_ITERATION_SECONDS = "lm_decode_iteration_seconds"
REQUESTS_TOTAL = "lm_requests_total"
SHED_TOTAL = "lm_shed_total"
DECODE_ERRORS_TOTAL = "lm_decode_errors_total"
PREFIX_HITS_TOTAL = "lm_prefix_cache_hits_total"
SPEC_TOKENS_TOTAL = "lm_spec_tokens_total"

# Final statuses whose KV contents are trustworthy at eviction: the
# pools were never torn down under the stream, so its pages can be
# published into the prefix index. "error" evictions (dispatch failure,
# fence trip) must NOT publish — the pools may have been rebuilt.
_PUBLISHABLE_STATUSES = ("ok", "deadline", "cancelled")

# Millisecond buckets for the prefill histogram (the default registry
# buckets are seconds-scaled; prefill is a handful of chunk dispatches).
_MS_BUCKETS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0,
)

class _PrefillDispatchError(RuntimeError):
    """A prefill dispatch failed with the pools donated to it — the KV
    pools may be deleted, unlike host-side failures after the dispatch
    (telemetry, sampling), which leave them intact. The distinction
    picks the recovery: pools-lost recovery evicts every active stream,
    so it must never run for a mere telemetry error."""


class LMRequest:
    """One admitted generation request and its token stream.

    The engine pushes ``{"kind": "token", ...}`` dicts followed by one
    ``{"kind": "done", "status": ...}`` into ``events``; the transport
    (HTTP handler, test, bench consumer) drains it. ``cancelled`` is the
    consumer's back-signal (client disconnect, queued-deadline 504): the
    scheduler observes it between iterations and frees the pages.
    """

    __slots__ = (
        "id", "prompt", "max_new_tokens", "deadline", "temperature",
        "seed", "rng", "enqueued_at", "events", "cancelled", "status",
        "tokens", "slot", "n_emitted", "span",
    )

    def __init__(
        self, prompt: np.ndarray, max_new_tokens: int, deadline: float,
        temperature: float = 0.0, seed: int = 0,
    ):
        # Run-scoped id (obs/trace): nonce-prefixed, collision-free
        # across replicas and restarts — the event/span join key.
        self.id = next_request_id()
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.deadline = float(deadline)
        self.temperature = float(temperature)
        self.seed = int(seed)
        # Built eagerly so an invalid seed raises HERE, on the
        # submitter's thread — not inside the scheduler's admission
        # path, whose failure recovery assumes a dispatch error and
        # tears down every active stream's KV state.
        self.rng = (
            np.random.default_rng(self.seed)
            if self.temperature > 0 else None
        )
        self.enqueued_at = time.monotonic()
        self.events: "queue.Queue[Dict[str, Any]]" = queue.Queue()
        self.cancelled = False
        self.status: Optional[str] = None
        self.tokens: List[int] = []
        self.slot: Optional[int] = None
        self.n_emitted = 0
        self.span = NULL_SPAN      # root trace span, set at admission

    def expired(self, now: Optional[float] = None) -> bool:
        return (time.monotonic() if now is None else now) >= self.deadline


class _Slot:
    """Host-side state of one batch slot (device state lives in the
    pools + the engine's position/table arrays)."""

    __slots__ = ("req", "pages", "total_len", "rng", "admitted_iter",
                 "admitted_at", "decode_span")

    def __init__(self, req: LMRequest, pages: List[int], total_len: int,
                 admitted_iter: int, admitted_at: float):
        self.req = req
        self.pages = pages
        self.total_len = total_len          # prompt + clamped max_new
        self.rng = req.rng
        self.admitted_iter = admitted_iter
        self.admitted_at = admitted_at      # queue pop, BEFORE prefill
        self.decode_span = NULL_SPAN        # the stream's decode window


class LMEngine:
    """Single-worker continuous-batching engine over a
    :class:`~...infer_transformer.PagedLMDecoder`.

    ``submit`` returns an :class:`LMRequest` (stream from its ``events``
    queue) or a shed-reason string (``queue_full`` | ``draining``), the
    same admission contract as :class:`~..core.ServeEngine`.
    """

    def __init__(
        self,
        decoder,                       # PagedLMDecoder
        *,
        queue_depth: int = 16,
        telemetry: Any = None,
        chaos: Any = None,
        decode_event_every: int = 50,
        max_consecutive_failures: int = 4,
        recompile_fence: bool = True,
        boot_compile_baseline: Optional[int] = None,
        prefix_cache: bool = False,
    ):
        self.decoder = decoder
        self.telemetry = telemetry
        self.chaos = chaos
        self.queue_depth = int(queue_depth)
        self.decode_event_every = max(int(decode_event_every), 1)
        self.max_consecutive_failures = int(max_consecutive_failures)
        self.allocator = PageAllocator(decoder.num_pages)
        # COW prompt-prefix sharing (SERVING.md "Prefix caching"):
        # admission forks cached pages, eviction publishes back.
        self.prefix_cache: Optional[PrefixCache] = (
            PrefixCache(self.allocator, decoder.page_size)
            if prefix_cache else None
        )
        # Self-speculative decoding is armed by the DECODER carrying a
        # verify program (make_paged_lm_decoder(spec_k=...)): rounds of
        # spec_k-1 packed drafts + one bf16 verify dispatch, host-side
        # accept/reject. Greedy streams only — a temperature stream in
        # the batch falls the whole round back to plain decode.
        self.spec_k = (
            int(decoder.spec_k)
            if getattr(decoder, "verify", None) is not None else 0
        )
        self.max_len = int(decoder.max_len)
        s, p = int(decoder.slots), int(decoder.max_pages)
        self._page_tables = np.zeros((s, p), np.int32)
        self._positions = np.zeros(s, np.int32)
        self._tokens = np.zeros(s, np.int32)
        self._slots: List[Optional[_Slot]] = [None] * s
        self._pools = None
        self._queue: deque[LMRequest] = deque()
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self.draining = False
        self._closed = False           # set by the final queue drain
        self.batch_seq = 0             # decode iterations dispatched
        self._consecutive_failures = 0
        # AOT boot-from-store (aot/, PERF.md "Cold start"): the server
        # passes the tracker mark it took BEFORE loading the decoder,
        # tightening the budget-0 fence from post-warmup to post-BOOT —
        # with stored executables, even the warmup dispatches must not
        # compile. None (cold boot) keeps the post-warmup baseline.
        self._boot_baseline = boot_compile_baseline
        self._compile_baseline: Optional[int] = None
        self.fence_error: Optional[str] = None
        self._thread: Optional[threading.Thread] = None

        from ...obs import default_registry, get_tracker

        self._tracker = get_tracker()
        # Device introspection (obs/costs, obs/profile): disabled =
        # one attribute check per dispatch; armed, the ledger feeds
        # measured per-program MFU and the profiler flag arms the
        # xplane step markers carrying this run's trace ids.
        self._ledger = get_ledger()
        self._profiler = get_profiler()
        self.pool_reserved_bytes = 0   # set once pools exist (start())
        self.page_bytes = 0
        # Spans ride the telemetry sink's tracer (obs/trace); the shared
        # NULL_TRACER keeps instrumentation a single attribute check
        # when telemetry is off.
        self.tracer = getattr(telemetry, "tracer", None) or NULL_TRACER
        reg = telemetry.registry if telemetry is not None else None
        if reg is None:
            reg = default_registry()
        self.registry = reg
        self.tokens_ctr = reg.counter(
            TOKENS_TOTAL, "LM tokens processed (phase=prefill|decode)"
        )
        self.occupancy_gauge = reg.gauge(
            PAGE_OCCUPANCY, "fraction of KV pages in use"
        )
        self.active_gauge = reg.gauge(
            ACTIVE_STREAMS, "generation streams holding a batch slot"
        )
        self.prefill_hist = reg.histogram(
            PREFILL_MS, "admission prefill wall time (ms)",
            buckets=_MS_BUCKETS,
        )
        self.iter_hist = reg.histogram(
            DECODE_ITERATION_SECONDS,
            "decode iteration wall time (= inter-token latency while "
            "the batch is stable)",
        )
        self.requests_ctr = reg.counter(
            REQUESTS_TOTAL, "LM requests by final status"
        )
        self.shed_ctr = reg.counter(
            SHED_TOTAL, "LM admission rejections by reason"
        )
        self.errors_ctr = reg.counter(
            DECODE_ERRORS_TOTAL, "decode dispatch failures (retried)"
        )
        self.prefix_hits_ctr = reg.counter(
            PREFIX_HITS_TOTAL,
            "prefix-cache admission lookups (result=hit|miss)",
        )
        self.spec_tokens_ctr = reg.counter(
            SPEC_TOKENS_TOTAL,
            "draft tokens by verify outcome (outcome=accepted|rejected)",
        )
        self._spec_drafted = 0         # cumulative drafts proposed
        self._spec_accepted = 0        # cumulative drafts accepted
        self._spec_rounds = 0
        self._sanitizer = Sanitizer(
            SanitizerConfig(
                recompile_fence=recompile_fence,
                recompile_budget=0,
                warmup_steps=0,
            ),
            telemetry=telemetry,
            registry=reg,
        ) if recompile_fence else None

    # -- introspection ------------------------------------------------------

    @property
    def active_streams(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    @property
    def queue_len(self) -> int:
        with self._cond:
            return len(self._queue)

    @property
    def recompiles_post_warmup(self) -> Optional[int]:
        if self._compile_baseline is None:
            return None
        return self._tracker.count - self._compile_baseline

    @property
    def spec_acceptance_rate(self) -> Optional[float]:
        """Accepted / drafted over the engine's lifetime (None until a
        spec round drafted anything)."""
        if self._spec_drafted == 0:
            return None
        return self._spec_accepted / self._spec_drafted

    def kv_pool_stats(self) -> Dict[str, Any]:
        """Paged-pool HBM attribution (OBSERVABILITY.md "Device
        profiling"): the pool's fixed reservation vs the bytes its
        in-use pages pin — a page leak becomes a numeric dashboard
        fact instead of a drain-time assertion. Also refreshes the
        ``kv_pool_*_bytes`` gauges."""
        in_use = self.allocator.used_count()
        stats = {
            "reserved_bytes": self.pool_reserved_bytes,
            "page_bytes": self.page_bytes,
            "pages_in_use": in_use,
            "in_use_bytes": in_use * self.page_bytes,
        }
        self.registry.gauge(
            "kv_pool_reserved_bytes", "paged KV pool reservation"
        ).set(stats["reserved_bytes"])
        self.registry.gauge(
            "kv_pool_in_use_bytes",
            "bytes pinned by in-use KV pages (pages_in_use x "
            "page_bytes)",
        ).set(stats["in_use_bytes"])
        return stats

    def prefix_cache_stats(self) -> Optional[Dict[str, Any]]:
        """Entry count + shared-page occupancy for /healthz, or None
        when the cache is off."""
        if self.prefix_cache is None:
            return None
        stats = self.prefix_cache.stats()
        stats["page_occupancy"] = round(
            stats["pages"] / max(self.allocator.capacity, 1), 4
        )
        return stats

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "LMEngine":
        """Warm the two compiled programs (prefill + decode) against the
        null page, pin the recompile baseline, start the scheduler."""
        import jax
        import jax.numpy as jnp

        dec = self.decoder
        pools = dec.init_pools()
        zeros_c = np.zeros(dec.prefill_chunk, np.int32)
        zeros_p = np.zeros(dec.max_pages, np.int32)
        # Scalars go in as 0-d int32 ndarrays (device_put), NOT numpy
        # scalars: jnp.asarray(np.int32(0)) eagerly compiles a convert
        # program, which an AOT boot-from-store (budget-0 fence pinned
        # at the BOOT mark) counts as a fence violation.
        pools, lp = dec.prefill(
            pools, jnp.asarray(zeros_c), jnp.asarray(zeros_p),
            jnp.asarray(np.asarray(0, np.int32)),
            jnp.asarray(np.asarray(0, np.int32)),
        )
        jax.block_until_ready(lp)
        pools, lp = dec.decode(
            pools, jnp.asarray(self._tokens),
            jnp.asarray(self._page_tables), jnp.asarray(self._positions),
        )
        jax.block_until_ready(lp)
        if self.spec_k:
            # Third program: the fixed-K verify dispatch must be warm
            # too, or the first spec round's compile trips the budget-0
            # fence (and with an AOT boot, the verify executable must
            # come from the store — the pair-miss discipline extends to
            # the triple).
            pools, vlp = dec.verify(
                pools,
                jnp.asarray(np.zeros(
                    (dec.slots, self.spec_k), np.int32
                )),
                jnp.asarray(self._page_tables),
                jnp.asarray(self._positions),
            )
            jax.block_until_ready(vlp)
        self._pools = pools
        # Pool-reservation accounting for the HBM census (/healthz
        # kv_pool, OBSERVABILITY.md "Device profiling"): the pools'
        # full byte footprint is fixed at boot; pages_in_use x
        # page_bytes against it makes a page leak a dashboard number.
        self.pool_reserved_bytes = int(sum(
            int(k.nbytes) + int(v.nbytes) for k, v in pools
        ))
        self.page_bytes = self.pool_reserved_bytes // max(
            dec.num_pages, 1
        )
        if self._ledger.enabled:
            # Per-program cost ledger (obs/costs). AOT-loaded programs
            # are Compiled — analyzed in place, zero compiles, so the
            # boot-pinned budget-0 fence stays green; cold-boot jitted
            # programs pay their throwaway analysis compile HERE,
            # before the post-warmup baseline is pinned below.
            self._ledger.record(
                "lm_prefill", dec.prefill, telemetry=self.telemetry,
                example_args=(
                    pools, jnp.asarray(zeros_c), jnp.asarray(zeros_p),
                    jnp.asarray(np.asarray(0, np.int32)),
                    jnp.asarray(np.asarray(0, np.int32)),
                ),
            )
            self._ledger.record(
                "lm_decode", dec.decode, telemetry=self.telemetry,
                example_args=(
                    pools, jnp.asarray(self._tokens),
                    jnp.asarray(self._page_tables),
                    jnp.asarray(self._positions),
                ),
            )
            if self.spec_k:
                self._ledger.record(
                    "lm_verify", dec.verify, telemetry=self.telemetry,
                    example_args=(
                        pools,
                        jnp.asarray(np.zeros(
                            (dec.slots, self.spec_k), np.int32
                        )),
                        jnp.asarray(self._page_tables),
                        jnp.asarray(self._positions),
                    ),
                )
        self._compile_baseline = (
            self._boot_baseline if self._boot_baseline is not None
            else self._tracker.mark()
        )
        if self._sanitizer is not None:
            # Pin the fence baseline: post-warmup for a cold boot, the
            # server's pre-load BOOT mark for an AOT store hit; every
            # later after_step enforces budget 0 against it.
            self._sanitizer.pin_baseline(self._compile_baseline)
        # Warmup record: which serving path the compiled programs carry
        # (kernels = Pallas page-walk attention + fused unpack-GEMM vs
        # the gather/popcount oracle) — the smoke asserts the armed path
        # from this event rather than trusting the CLI flag made it here.
        kernels = bool(getattr(dec, "kernels", False))
        if self.telemetry is not None:
            self.telemetry.emit(
                "lm_warmup", programs=3 if self.spec_k else 2,
                kernels=kernels, spec_k=self.spec_k,
            )
        log.info(
            "lm engine warm: %d programs, kernels=%s",
            3 if self.spec_k else 2, kernels,
        )
        self._thread = threading.Thread(
            target=self._run, name="lm-engine", daemon=True
        )
        self._thread.start()
        return self

    def begin_drain(self) -> None:
        self.draining = True

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop admitting, wait for queued + streaming work to finish.
        Returns False on timeout (callers still stop)."""
        self.begin_drain()
        deadline = time.monotonic() + timeout
        while self.queue_len or self.active_streams:
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.01)
        return True

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self.prefix_cache is not None:
            # The cache must be fully evictable at teardown: release
            # every cache-held page reference so pages_in_use drains to
            # 0 (live forks, if any remain, keep their own references).
            self.prefix_cache.clear()
            self.occupancy_gauge.set(self.allocator.occupancy())

    # -- admission (transport threads) --------------------------------------

    def submit(
        self, prompt, max_new_tokens: int, deadline: float, *,
        temperature: float = 0.0, seed: int = 0,
        ctx: Optional[TraceContext] = None,
    ):
        """Admit or shed. Returns an :class:`LMRequest` or a shed-reason
        string. Validation beyond shape limits (prompt length vs
        ``max_len``) is the transport's job — it owns the 4xx replies.
        ``ctx`` is an adopted ``x-jg-trace`` context (obs/trace): the
        stream's span tree joins the client's trace."""
        if self.draining or self._stop.is_set():
            return self._shed("draining", ctx=ctx)
        if self.fence_error is not None or (
            self._thread is not None and not self._thread.is_alive()
        ):
            # The scheduler is dead (recompile fence or a fatal crash):
            # queueing would strand the request until its deadline.
            # Shed immediately — and visibly (health() reports failed).
            return self._shed("engine_failed", ctx=ctx)
        req = LMRequest(
            prompt, max_new_tokens, deadline,
            temperature=temperature, seed=seed,
        )
        req.span = self.tracer.start(
            "lm.request", kind="request", ctx=ctx, fresh=True,
            id=req.id, prompt_tokens=int(req.prompt.shape[0]),
            max_new_tokens=req.max_new_tokens,
        )
        with self._cond:
            if self._closed:
                # The scheduler drained the queue for the last time
                # (fence trip or stop) BETWEEN the liveness check above
                # and here — appending would strand the request with no
                # thread left to pop it.
                reason = "engine_failed"
            elif len(self._queue) >= self.queue_depth:
                reason = "queue_full"
            else:
                self._queue.append(req)
                self._cond.notify()
                return req
        req.span.end("shed", reason=reason)
        return self._shed(reason, spanned=True)

    def _shed(
        self, reason: str, *, ctx: Optional[TraceContext] = None,
        spanned: bool = False,
    ) -> str:
        self.shed_ctr.inc(reason=reason)
        self.requests_ctr.inc(status="shed")
        if not spanned and self.tracer.enabled:
            # Sheds are (zero-length) spans too, joinable to the
            # client's trace — same contract as serve/core.
            now = time.monotonic()
            self.tracer.record(
                "lm.request", kind="request", t0=now, t1=now,
                ctx=ctx, fresh=True, status="shed", reason=reason,
            )
        if self.telemetry is not None:
            self.telemetry.emit(
                "shed", reason=reason, queue_depth=self.queue_len,
                engine="lm",
            )
        return reason

    # -- scheduler ----------------------------------------------------------

    def _run(self) -> None:
        while True:
            try:
                if self._stop.is_set():
                    # Before admitting: a stop with requests still
                    # queued must cancel them, not pay a full prefill
                    # and stream one token into a 200 it will
                    # immediately kill.
                    self._cancel_all("engine stopped")
                    return
                self._admit_ready()
                if self.active_streams == 0:
                    # Idle (covers draining-with-nothing-left too):
                    # sleep until work or stop; the loop-top check
                    # handles the stop on the next pass.
                    with self._cond:
                        if not self._queue and not self._stop.is_set():
                            self._cond.wait(0.02)
                    continue
                if self._stop.is_set():
                    self._cancel_all("engine stopped")
                    return
                self._decode_once()
            except RecompileFenceError as e:
                # Budget-0 fence: a post-warmup compile means the ONE-
                # signature contract broke. Fail loudly and visibly.
                self.fence_error = str(e)
                log.error("lm-engine recompile fence tripped: %s", e)
                self._evict_all("error", f"recompile fence: {e}")
                # Queued work would otherwise strand until its
                # deadlines; submit() sheds engine_failed from now on.
                self._cancel_all(f"recompile fence: {e}")
                return
            except Exception:
                log.exception(
                    "lm-engine iteration %d failed; scheduler continues",
                    self.batch_seq,
                )
                time.sleep(0.01)

    def _free_slot_index(self) -> Optional[int]:
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return None

    def _purge_dead_queued(self) -> None:
        """Drop expired/cancelled entries from the bounded queue even
        when every slot is busy — a 504'd request must not keep holding
        a queue_depth token and shed live traffic as ``queue_full`` for
        the rest of some long stream's lifetime."""
        with self._cond:
            dead = [r for r in self._queue
                    if r.expired() or r.cancelled]
            if not dead:
                return
            kept = [r for r in self._queue
                    if not (r.expired() or r.cancelled)]
            self._queue.clear()
            self._queue.extend(kept)
        for req in dead:
            # Deadline before cancellation (same precedence as the pop
            # path below): the 504 waiter sets both.
            if req.expired():
                self._finish_unslotted(req, "deadline",
                                       "deadline exceeded in queue")
            else:
                self._finish_unslotted(req, "cancelled",
                                       "cancelled while queued")

    def _admit_ready(self) -> None:
        """Pop queued requests into free slots while pages allow —
        runs between decode iterations, so a request admitted here joins
        sequences already mid-generation."""
        self._purge_dead_queued()
        while True:
            slot = self._free_slot_index()
            if slot is None:
                return
            with self._cond:
                if not self._queue:
                    return
                req = self._queue.popleft()
            # Deadline before cancellation: the 504 path sets BOTH (the
            # waiter cancels after replying), and "deadline" is the
            # truth the event log should carry.
            if req.expired():
                self._finish_unslotted(req, "deadline",
                                       "deadline exceeded in queue")
                continue
            if req.cancelled:
                self._finish_unslotted(req, "cancelled",
                                       "cancelled while queued")
                continue
            total = min(
                len(req.prompt) + req.max_new_tokens, self.max_len
            )
            ps = self.decoder.page_size
            need_total = pages_needed(total, ps)
            if need_total > self.allocator.capacity:
                # Would never fit even on an idle engine: failing it now
                # beats wedging the FIFO head forever.
                self._finish_unslotted(
                    req, "error",
                    f"request needs {need_total} pages, pool holds "
                    f"{self.allocator.capacity}",
                )
                continue
            alloc_t0 = time.monotonic()
            cached_tokens, forked = 0, []
            if self.prefix_cache is not None:
                # Longest cached full-page prefix, capped so at least
                # ONE suffix token prefills (admission samples the
                # first generated token from the suffix's log-probs).
                cached_tokens, forked = self.prefix_cache.lookup(
                    req.prompt, len(req.prompt) - 1
                )
            need = need_total - len(forked)
            pages = self.allocator.alloc(need)
            if pages is None and self.prefix_cache is not None:
                # Pool pressure: drop LRU cache-only entries and retry
                # before giving up the admission.
                shortfall = need - self.allocator.free_count()
                if self.prefix_cache.evict(shortfall) > 0:
                    pages = self.allocator.alloc(need)
            if pages is None:
                # Not enough KV memory: requeue at the head and let
                # running sequences finish — eviction frees pages. The
                # forked prefix references go back too (the next
                # attempt re-forks).
                if forked:
                    self.allocator.free(forked)
                with self._cond:
                    self._queue.appendleft(req)
                return
            if self.prefix_cache is not None:
                # Count hit/miss only for admissions that proceed: a
                # pool-pressure requeue re-runs the lookup every
                # scheduler pass, and counting those retries would
                # inflate the hit rate by orders of magnitude.
                self.prefix_cache.note_result(bool(forked))
                self.prefix_hits_ctr.inc(
                    result="hit" if forked else "miss"
                )
            pages = forked + pages     # table order: prefix first
            if self.tracer.enabled:
                # Queue wait ends when the scheduler starts working on
                # the request (= alloc start); page_alloc follows it.
                # Sequential, non-overlapping children — the critical-
                # path attribution sums child self-times, so sibling
                # intervals must not overlap.
                self.tracer.record(
                    "lm.queue", kind="queue", parent=req.span,
                    t0=req.enqueued_at, t1=alloc_t0,
                )
                self.tracer.record(
                    "lm.page_alloc", kind="page_alloc", parent=req.span,
                    t0=alloc_t0, t1=time.monotonic(),
                    pages=len(pages), need=need,
                    forked=len(forked),
                )
            try:
                self._prefill_into_slot(
                    req, slot, pages, total, cached_tokens
                )
            except Exception as e:
                log.exception("lm-engine prefill for request %s failed",
                              req.id)
                hazard = isinstance(e, _PrefillDispatchError)
                cause = e.__cause__ if hazard and e.__cause__ else e
                detail = (
                    f"prefill failure: {type(cause).__name__}: {cause}"
                )
                st = self._slots[slot]
                if st is not None and st.req is req:
                    # The failure landed AFTER the slot assignment
                    # (e.g. the lm_admit emit raised): the slot owns
                    # the pages now. _evict frees them exactly once
                    # and delivers the done event — freeing here would
                    # hand live pages to the next request.
                    self._evict(slot, "error", detail)
                elif req.slot is None and req.status is None:
                    # Failed before ownership transferred: the pages
                    # are still the handler's to return.
                    self.allocator.free(pages)
                    self._finish_unslotted(req, "error", detail)
                # else: the request already finished (a post-eviction
                # emit raised) — its pages are freed and its status
                # recorded; nothing is owed here.
                if hazard:
                    # The pools were donated to the failed dispatch and
                    # may be deleted — every later iteration would die.
                    # Same recovery as a decode dispatch failure: fail
                    # actives, rebuild fresh pools. Host-side failures
                    # after the dispatches (telemetry, sampling) leave
                    # the pools intact and must NOT take this path.
                    self._dispatch_failure(cause)

    def _prefill_into_slot(
        self, req: LMRequest, slot: int, pages: List[int], total: int,
        cached_tokens: int = 0,
    ) -> None:
        import jax
        import jax.numpy as jnp

        dec = self.decoder
        admitted_at = time.monotonic()      # queue wait ends HERE: the
        t0 = time.perf_counter()            # queue/prefill split must
        table = np.zeros(dec.max_pages, np.int32)  # not double-count
        table[: len(pages)] = pages
        plen = len(req.prompt)
        # Prefix-cache hit: the first cached_tokens positions' K/V
        # already sit in the forked pages (page-aligned by
        # construction) — prefill runs only on the uncached suffix,
        # in the same fixed-chunk dispatches, attending through the
        # shared pages for its causal history.
        suffix = plen - cached_tokens       # >= 1 (lookup is capped)
        chunk = dec.prefill_chunk
        padded = -(-suffix // chunk) * chunk
        prompt = np.zeros(padded, np.int32)
        prompt[:suffix] = req.prompt[cached_tokens:]
        table_j = jnp.asarray(table)
        # 0-d ndarrays, not numpy scalars: a scalar would eagerly
        # compile a convert program and trip the boot-pinned fence.
        length_j = jnp.asarray(np.asarray(plen, np.int32))
        lp_last = None
        last_start = cached_tokens
        try:
            for off in range(0, padded, chunk):
                start = cached_tokens + off
                self._pools, clp = dec.prefill(
                    self._pools, jnp.asarray(prompt[off:off + chunk]),
                    table_j, jnp.asarray(np.asarray(start, np.int32)),
                    length_j,
                )
                lp_last = clp
                last_start = start
            # sync: admission, not hot loop — a deferred device error
            # surfaces here, still inside the donation-hazard region
            lp_host = np.asarray(lp_last)
        except Exception as e:
            raise _PrefillDispatchError(
                f"prefill dispatch for request {req.id}"
            ) from e
        prefill_ms = (time.perf_counter() - t0) * 1e3
        self.prefill_hist.observe(prefill_ms)
        if self._ledger.enabled:
            # padded // chunk fixed-shape dispatches (obs/costs).
            self._ledger.observe(
                "lm_prefill", prefill_ms / 1e3,
                n=max(padded // chunk, 1),
            )
        # Counter delta = tokens actually prefilled: a cache hit's
        # skipped work is visible as lm_tokens_total{phase=prefill}
        # growing by the suffix only (the CI smoke asserts on this).
        self.tokens_ctr.inc(suffix, phase="prefill")
        st = _Slot(req, pages, total, self.batch_seq, admitted_at)
        if self.tracer.enabled:
            # The queue + page_alloc children were banked at admission
            # (_admit_ready); prefill picks up from the same marks the
            # prefill_ms event field is derived from, so spans and
            # events can never disagree.
            self.tracer.record(
                "lm.prefill", kind="prefill", parent=req.span,
                t0=admitted_at, t1=admitted_at + prefill_ms / 1e3,
                prompt_tokens=plen, chunks=padded // chunk, slot=slot,
                cached_tokens=cached_tokens,
            )
            # The decode window: first token out of prefill -> evict.
            # A live span (ended by _evict) so a request that dies
            # mid-stream still closes its tree.
            st.decode_span = self.tracer.start(
                "lm.decode", kind="decode", parent=req.span, slot=slot,
            )
        # First generated token comes straight out of prefill: the
        # prompt's last position predicts position plen.
        first = self._sample_token(
            req, lp_host[plen - 1 - last_start], st.rng
        )
        self._slots[slot] = st
        req.slot = slot
        self._page_tables[slot] = table
        self._positions[slot] = plen       # next decode writes pos plen
        self._tokens[slot] = first
        self.active_gauge.set(self.active_streams)
        self.occupancy_gauge.set(self.allocator.occupancy())
        if self.telemetry is not None:
            self.telemetry.emit(
                "lm_admit",
                id=req.id, slot=slot, prompt_tokens=plen,
                prefill_tokens=suffix, cached_tokens=cached_tokens,
                max_new_tokens=req.max_new_tokens, pages=len(pages),
                iteration=self.batch_seq,
                queue_ms=round((st.admitted_at - req.enqueued_at) * 1e3, 3),
                prefill_ms=round(prefill_ms, 3),
                page_occupancy=round(self.allocator.occupancy(), 4),
            )
            if cached_tokens:
                self.telemetry.emit(
                    "lm_prefix_hit",
                    id=req.id, slot=slot, prompt_tokens=plen,
                    cached_tokens=cached_tokens,
                    pages_forked=pages_needed(
                        cached_tokens, dec.page_size
                    ),
                    prefill_tokens=suffix,
                    prefill_ms=round(prefill_ms, 3),
                )
        self._emit_token(req, first)
        self._maybe_finish(slot)

    def _sample_token(
        self, req: LMRequest, lp: np.ndarray, rng
    ) -> int:
        """Greedy at temperature 0, else categorical from the slot's own
        host RNG — sampling is host-side numpy so the compiled decode
        signature stays sampling-free (and per-request temperatures
        don't multiply program variants)."""
        if req.temperature > 0 and rng is not None:
            logits = lp / req.temperature
            logits = logits - logits.max()
            p = np.exp(logits)
            p /= p.sum()
            return int(rng.choice(len(p), p=p))
        return int(np.argmax(lp))

    def _emit_token(self, req: LMRequest, token: int) -> None:
        req.n_emitted += 1
        req.tokens.append(int(token))
        self.tokens_ctr.inc(phase="decode")
        req.events.put({
            "kind": "token", "i": req.n_emitted - 1, "token": int(token),
        })

    def _decode_once(self) -> None:
        self.batch_seq += 1
        self._expire_active()
        if self.active_streams == 0:
            return
        # Speculative rounds need every active slot greedy: accepted-
        # prefix verification is an argmax identity, and a temperature
        # slot's host RNG must consume exactly one draw per emitted
        # token — so a mixed batch falls back to plain decode for the
        # whole round (SERVING.md "Speculative decoding").
        if self.spec_k and all(
            s is None or s.rng is None for s in self._slots
        ):
            self._spec_round()
        else:
            self._plain_round()

    def _plain_round(self) -> None:
        import jax
        import jax.numpy as jnp

        # ONE span per decode iteration, batching all active slots (the
        # iteration-level scheduler's unit of work): while it is the
        # scheduler thread's current span, a chaos fault fired below
        # parents its own span here — the previously invisible gap
        # between lm_admit and lm_evict becomes a causal lane.
        iter_span = self.tracer.start(
            "lm.decode_iter", kind="decode_iter",
            iteration=self.batch_seq, active=self.active_streams,
        )
        with iter_span:
            if self.chaos is not None and self.chaos.active:
                try:
                    self.chaos.on_infer(step=self.batch_seq)
                except Exception as e:
                    # Raised BEFORE the dispatch: nothing was donated,
                    # the pools are intact, the iteration can simply be
                    # retried (bounded by max_consecutive_failures).
                    iter_span.end("error", error=type(e).__name__)
                    self._record_predispatch_failure(e)
                    return
            t0 = time.perf_counter()
            try:
                if self._profiler.active:
                    # Capture live: mark the dispatch in the xplane
                    # with this run's trace id (obs/profile) so the
                    # device profile joins the host span trees.
                    with jax.profiler.StepTraceAnnotation(
                        STEP_MARKER, step_num=self.batch_seq,
                        program="lm_decode",
                        jg_trace=iter_span.trace_id
                        or self.tracer.run_trace,
                    ):
                        self._pools, lp = self.decoder.decode(
                            self._pools,
                            jnp.asarray(self._tokens),
                            jnp.asarray(self._page_tables),
                            jnp.asarray(self._positions),
                        )
                        lp_host = np.asarray(lp)
                else:
                    self._pools, lp = self.decoder.decode(
                        self._pools,
                        jnp.asarray(self._tokens),
                        jnp.asarray(self._page_tables),
                        jnp.asarray(self._positions),
                    )
                    lp_host = np.asarray(lp)  # per-iteration sync point
            except Exception as e:
                # A failure INSIDE the dispatch cannot be retried: the
                # pools were donated to it and may already be deleted.
                # Fail every active stream loudly and rebuild fresh
                # pools so the engine keeps serving future requests
                # (same compiled programs — the shapes are unchanged,
                # no recompile).
                iter_span.end("error", error=type(e).__name__)
                self._dispatch_failure(e)
                return
            dt = time.perf_counter() - t0
            iter_span.end("ok", iter_ms=round(dt * 1e3, 3))
        self._consecutive_failures = 0
        self.iter_hist.observe(dt)
        if self._ledger.enabled:
            self._ledger.observe("lm_decode", dt)
        if self._sanitizer is not None:
            self._sanitizer.after_step(step=self.batch_seq)
        for slot, st in enumerate(self._slots):
            if st is None:
                continue
            nxt = self._sample_token(st.req, lp_host[slot], st.rng)
            self._positions[slot] += 1
            self._tokens[slot] = nxt
            self._emit_token(st.req, nxt)
            self._maybe_finish(slot)
        if self.batch_seq % self.decode_event_every == 0:
            if self.telemetry is not None:
                self.telemetry.emit(
                    "lm_decode",
                    iteration=self.batch_seq,
                    active=self.active_streams,
                    queue_depth=self.queue_len,
                    iter_ms=round(dt * 1e3, 3),
                    page_occupancy=round(self.allocator.occupancy(), 4),
                    recompiles_post_warmup=self.recompiles_post_warmup,
                )

    def _spec_round(self) -> None:
        """One self-speculative round for all active (greedy) slots:
        ``spec_k - 1`` drafts through the packed decode program, ONE
        dense-bf16 verify dispatch scoring the whole window, host-side
        accept/reject (SERVING.md "Speculative decoding").

        Emits ``a + 1`` tokens per slot — the accepted draft prefix
        plus the verifier's correction (or bonus) token — so greedy
        output is token-identical to the verifier alone by
        construction: every emitted token IS a verifier argmax, drafts
        merely prepay the positions the verifier then scores in one
        large-M dispatch. The compiled signatures are fixed (K never
        varies with acceptance), keeping the budget-0 fence green.
        """
        import jax
        import jax.numpy as jnp

        k_win = self.spec_k
        iter_span = self.tracer.start(
            "lm.decode_iter", kind="decode_iter",
            iteration=self.batch_seq, active=self.active_streams,
            spec_k=k_win,
        )
        with iter_span:
            if self.chaos is not None and self.chaos.active:
                try:
                    self.chaos.on_infer(step=self.batch_seq)
                except Exception as e:
                    # Pre-dispatch: nothing donated, pools intact —
                    # the round is simply retried (bounded by
                    # max_consecutive_failures), same as plain decode.
                    iter_span.end("error", error=type(e).__name__)
                    self._record_predispatch_failure(e)
                    return
            t0 = time.perf_counter()
            tables_j = jnp.asarray(self._page_tables)
            window = np.zeros((len(self._slots), k_win), np.int32)
            window[:, 0] = self._tokens    # input 0: the pending token
            # Capture live: one step marker spans the whole spec round
            # (drafts + verify — the scheduler's unit of work), carrying
            # the trace id the host span trees use (obs/profile).
            prof_ann = (
                jax.profiler.StepTraceAnnotation(
                    STEP_MARKER, step_num=self.batch_seq,
                    program="lm_spec_round",
                    jg_trace=iter_span.trace_id or self.tracer.run_trace,
                ) if self._profiler.active else None
            )
            if prof_ann is not None:
                prof_ann.__enter__()
            try:
                # Draft phase: k_win - 1 packed small-M dispatches.
                # Positions/tokens advance in LOCAL copies — the
                # engine's arrays only move when the host accepts.
                draft_t0 = time.monotonic()
                d_tokens = self._tokens.copy()
                d_positions = self._positions.copy()
                for j in range(1, k_win):
                    self._pools, lp = self.decoder.decode(
                        self._pools, jnp.asarray(d_tokens), tables_j,
                        jnp.asarray(d_positions),
                    )
                    d_tokens = np.argmax(
                        np.asarray(lp), axis=-1
                    ).astype(np.int32)
                    window[:, j] = d_tokens
                    d_positions = d_positions + 1
                draft_t1 = time.monotonic()
                # Verify phase: ONE large-M bf16 dispatch scores every
                # window position (and overwrites the drafts' K/V with
                # the verifier's canonical values).
                self._pools, vlp = self.decoder.verify(
                    self._pools, jnp.asarray(window), tables_j,
                    jnp.asarray(self._positions),
                )
                v_host = np.asarray(vlp)   # (S, K, vocab) — the sync
                verify_t1 = time.monotonic()
            except Exception as e:
                # Mid-dispatch failure with the pools donated: KV is
                # gone for everyone — same recovery as plain decode.
                iter_span.end("error", error=type(e).__name__)
                self._dispatch_failure(e)
                return
            finally:
                if prof_ann is not None:
                    prof_ann.__exit__(None, None, None)
            dt = time.perf_counter() - t0
            if self.tracer.enabled:
                self.tracer.record(
                    "lm.draft", kind="draft", parent=iter_span,
                    t0=draft_t0, t1=draft_t1, drafts=k_win - 1,
                )
                self.tracer.record(
                    "lm.verify", kind="verify", parent=iter_span,
                    t0=draft_t1, t1=verify_t1, window=k_win,
                )
            iter_span.end("ok", iter_ms=round(dt * 1e3, 3))
        self._consecutive_failures = 0
        self.iter_hist.observe(dt)
        if self._ledger.enabled:
            # Measured-MFU feed per program: the k_win-1 packed drafts
            # and the one dense-bf16 verify dispatch (obs/costs).
            if k_win > 1:
                self._ledger.observe(
                    "lm_decode", draft_t1 - draft_t0, n=k_win - 1
                )
            self._ledger.observe("lm_verify", verify_t1 - draft_t1)
        if self._sanitizer is not None:
            self._sanitizer.after_step(step=self.batch_seq)
        greedy = np.argmax(v_host, axis=-1)          # (S, K)
        round_accepted = round_drafted = 0
        for slot, st in enumerate(self._slots):
            if st is None:
                continue
            # Accept the longest draft prefix the verifier agrees
            # with: window[j+1] (draft) vs greedy[j] (the verifier's
            # choice after consuming window[j]).
            a = 0
            while (a < k_win - 1
                   and window[slot, a + 1] == greedy[slot, a]):
                a += 1
            round_drafted += k_win - 1
            round_accepted += a
            # Emit the accepted drafts plus the verifier's correction
            # (or, at full acceptance, its bonus token). Positions
            # advance past every emitted-except-pending token; the
            # correction becomes the new pending token.
            self._positions[slot] += a + 1
            self._tokens[slot] = int(greedy[slot, a])
            emit = [int(window[slot, j]) for j in range(1, a + 1)]
            emit.append(int(greedy[slot, a]))
            for token in emit:
                self._emit_token(st.req, token)
                self._maybe_finish(slot)
                if self._slots[slot] is None:
                    break                  # finished mid-window
        self._spec_rounds += 1
        self._spec_drafted += round_drafted
        self._spec_accepted += round_accepted
        if round_accepted:
            self.spec_tokens_ctr.inc(round_accepted, outcome="accepted")
        if round_drafted - round_accepted:
            self.spec_tokens_ctr.inc(
                round_drafted - round_accepted, outcome="rejected"
            )
        if self.batch_seq % self.decode_event_every == 0:
            if self.telemetry is not None:
                rate = self.spec_acceptance_rate
                self.telemetry.emit(
                    "lm_spec_round",
                    iteration=self.batch_seq,
                    active=self.active_streams,
                    spec_k=k_win,
                    accepted=round_accepted,
                    drafted=round_drafted,
                    acceptance_rate=(
                        round(rate, 4) if rate is not None else None
                    ),
                    iter_ms=round(dt * 1e3, 3),
                    page_occupancy=round(
                        self.allocator.occupancy(), 4
                    ),
                    recompiles_post_warmup=self.recompiles_post_warmup,
                )

    def _record_predispatch_failure(self, e: Exception) -> None:
        self._consecutive_failures += 1
        self.errors_ctr.inc(kind=type(e).__name__)
        log.warning(
            "lm-engine decode iteration %d failed (%s: %s) — attempt "
            "%d/%d", self.batch_seq, type(e).__name__, e,
            self._consecutive_failures, self.max_consecutive_failures,
        )
        if self.telemetry is not None:
            self.telemetry.emit(
                "lm_decode_error", iteration=self.batch_seq,
                error=f"{type(e).__name__}: {e}"[:500],
                consecutive=self._consecutive_failures,
            )
        if self._consecutive_failures >= self.max_consecutive_failures:
            # The backend is presumed wedged: fail every stream loudly
            # rather than spinning forever. Chaos-injected transients
            # (infer_error) stay below the cap and are simply retried —
            # they fire before the dispatch, so nothing was donated and
            # the pools are untouched.
            self._evict_all(
                "error",
                f"{self._consecutive_failures} consecutive decode "
                f"failures (last: {type(e).__name__}: {e})",
            )
            self._consecutive_failures = 0

    def _dispatch_failure(self, e: Exception) -> None:
        """A jitted call failed mid-execution: with donated pools the
        KV memory is gone, so every active stream dies here — but the
        ENGINE survives, on freshly initialized pools."""
        self.errors_ctr.inc(kind=type(e).__name__)
        log.error(
            "lm-engine dispatch failed at iteration %d (%s: %s): KV "
            "pools lost (donated) — failing %d active stream(s), "
            "rebuilding pools", self.batch_seq, type(e).__name__, e,
            self.active_streams,
        )
        if self.telemetry is not None:
            self.telemetry.emit(
                "lm_decode_error", iteration=self.batch_seq,
                error=f"{type(e).__name__}: {e}"[:500],
                fatal_to_streams=True,
            )
        self._evict_all(
            "error",
            f"decode dispatch failed, KV state lost "
            f"({type(e).__name__}: {e})",
        )
        if self.prefix_cache is not None:
            # The rebuilt pools make every cached page's CONTENTS
            # garbage even though the page ids stay valid: serving a
            # stale prefix would be silently-wrong log-probs. Drop the
            # whole index (error evictions above did not publish).
            dropped = self.prefix_cache.clear()
            if dropped:
                log.warning(
                    "prefix cache invalidated after dispatch failure "
                    "(%d entr%s dropped)", dropped,
                    "y" if dropped == 1 else "ies",
                )
        self._pools = self.decoder.init_pools()

    def _expire_active(self) -> None:
        now = time.monotonic()
        for slot, st in enumerate(self._slots):
            if st is None:
                continue
            if st.req.cancelled:
                self._evict(slot, "cancelled", "client went away")
            elif st.req.expired(now):
                self._evict(slot, "deadline",
                            "deadline exceeded mid-stream")

    def _maybe_finish(self, slot: int) -> None:
        st = self._slots[slot]
        if st is None:
            return
        req = st.req
        if req.n_emitted >= req.max_new_tokens:
            self._evict(slot, "ok", "")
        elif len(req.prompt) + req.n_emitted >= st.total_len:
            self._evict(slot, "ok", "max_len reached")

    # -- eviction / completion ----------------------------------------------

    def _evict(self, slot: int, status: str, detail: str) -> None:
        st = self._slots[slot]
        if st is None:
            return
        self._slots[slot] = None
        self._page_tables[slot] = 0
        self._positions[slot] = 0
        self._tokens[slot] = 0
        req = st.req
        published = 0
        if (self.prefix_cache is not None
                and status in _PUBLISHABLE_STATUSES
                and req.n_emitted >= 1):
            # Publish instead of free: the sequence's written K/V —
            # prompt plus every emitted token except the pending last
            # one (its K/V was never written) — re-enters the prefix
            # index; full pages transfer their reference to the cache,
            # duplicates and the partial tail are released.
            written = len(req.prompt) + req.n_emitted - 1
            seq = np.concatenate([
                req.prompt,
                np.asarray(req.tokens[:req.n_emitted - 1], np.int32),
            ])[:written]
            published = self.prefix_cache.insert(seq, st.pages)
        else:
            self.allocator.free(st.pages)
        req.slot = None
        st.decode_span.end(status, tokens=req.n_emitted,
                           iteration=self.batch_seq)
        self._finish(req, status, detail, slot=slot,
                     pages_freed=len(st.pages),
                     pages_published=published,
                     wall_ms=round(
                         (time.monotonic() - st.admitted_at) * 1e3, 3))
        self.active_gauge.set(self.active_streams)
        self.occupancy_gauge.set(self.allocator.occupancy())

    def _evict_all(self, status: str, detail: str) -> None:
        for slot, st in enumerate(self._slots):
            if st is not None:
                self._evict(slot, status, detail)

    def _cancel_all(self, detail: str) -> None:
        # Close admission under the queue lock FIRST: a submit() racing
        # past the liveness checks either lands before this (and is
        # drained below) or observes _closed and sheds — never strands.
        with self._cond:
            self._closed = True
        self._evict_all("cancelled", detail)
        while True:
            with self._cond:
                if not self._queue:
                    return
                req = self._queue.popleft()
            self._finish_unslotted(req, "cancelled", detail)

    def _finish_unslotted(
        self, req: LMRequest, status: str, detail: str
    ) -> None:
        if self.tracer.enabled and req.span is not NULL_SPAN:
            # Never admitted: its whole life WAS queue wait — the span
            # tree says so explicitly (a queued-deadline 504 shows up
            # queue-dominated in tail attribution, as it should).
            self.tracer.record(
                "lm.queue", kind="queue", parent=req.span,
                t0=req.enqueued_at, t1=time.monotonic(),
            )
        self._finish(req, status, detail, slot=None, pages_freed=0,
                     wall_ms=round(
                         (time.monotonic() - req.enqueued_at) * 1e3, 3))

    def _finish(
        self, req: LMRequest, status: str, detail: str, *,
        slot: Optional[int], pages_freed: int, wall_ms: float,
        pages_published: int = 0,
    ) -> None:
        req.status = status
        self.requests_ctr.inc(status=status)
        req.span.end(status, tokens_emitted=req.n_emitted,
                     iteration=self.batch_seq)
        if self.telemetry is not None:
            fields: Dict[str, Any] = {
                "id": req.id, "status": status, "slot": slot,
                "tokens_emitted": req.n_emitted,
                "pages_freed": pages_freed, "wall_ms": wall_ms,
                "iteration": self.batch_seq,
            }
            if pages_published:
                fields["pages_published"] = pages_published
            if detail:
                fields["detail"] = detail[:500]
            try:
                self.telemetry.emit("lm_evict", **fields)
            except Exception:
                # Telemetry must never disrupt serving: the client's
                # terminal event below is owed regardless, and an
                # exception escaping _evict would abort the rest of the
                # iteration's slot loop.
                log.exception("lm_evict emit failed (telemetry only)")
        req.events.put({
            "kind": "done", "status": status, "n": req.n_emitted,
            "id": req.id, **({"detail": detail} if detail else {}),
        })
