"""Radix prefix index over the paged KV pool — copy-on-write prompt
sharing for the continuous-batching LM engine (SERVING.md "Prefix
caching").

Requests sharing a prompt prefix (system prompts at fleet scale) should
prefill it ONCE. The block-paged KV cache (ops/paged_kv.py) is exactly
the right substrate: a full page of K/V is an immutable function of the
``page_size`` tokens that produced it (plus everything before them), so
a page can be shared read-only between sequences — the RadixAttention /
PagedAttention prefix-sharing idea, at page granularity.

Structure: a trie whose edges are **page-size token blocks**. A node
holds the page id of the K/V for its block, with the cache owning one
allocator reference (``PageAllocator`` refcounts). The engine:

  * at admission, walks the longest matching chain of *full* blocks
    (capped at ``prompt_len - 1`` — at least one suffix token must
    prefill so admission has log-probs to sample the first token from),
    ``fork``s the hit pages into the new sequence's page table, and
    prefills only the uncached suffix;
  * at eviction, publishes the sequence's full pages back into the trie
    (ownership of the page reference transfers from the sequence to the
    cache; blocks already present just release the duplicate);
  * under pool pressure, evicts leaf entries in LRU order — but only
    entries whose page refcount is 1, i.e. held by nobody but the
    cache. A page a live sequence still maps is never freed from under
    it.

Only FULL pages are shared: divergence past a shared prefix starts
exactly at the next page boundary, so forked pages are never written by
the forking sequence — copy-on-write where the copy never needs to
happen.

Thread-safety: the engine's scheduler thread is the only mutator; the
lock exists so the HTTP handlers' ``stats()`` reads (healthz) see a
consistent snapshot.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...ops.paged_kv import PageAllocator

__all__ = ["PrefixCache"]


class _Node:
    """One cached page: the K/V of ``block`` (a page_size token tuple)
    given the path from the root."""

    __slots__ = ("block", "page", "children", "parent", "last_used")

    def __init__(self, block: Tuple[int, ...], page: int,
                 parent: Optional["_Node"]):
        self.block = block
        self.page = int(page)
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.parent = parent
        self.last_used = 0


class PrefixCache:
    def __init__(self, allocator: PageAllocator, page_size: int):
        self.allocator = allocator
        self.page_size = int(page_size)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[int, ...], _Node] = {}  # root edges
        self._entries = 0
        self._clock = 0          # monotonic touch counter (LRU order)
        self.hits = 0
        self.misses = 0

    # -- queries -------------------------------------------------------------

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": self._entries,
                "pages": self._entries,        # one page per entry
                "hits": self.hits,
                "misses": self.misses,
            }

    @property
    def entries(self) -> int:
        with self._lock:
            return self._entries

    # -- admission: longest cached prefix ------------------------------------

    def lookup(self, tokens: np.ndarray, max_tokens: int
               ) -> Tuple[int, List[int]]:
        """Longest cached full-block prefix of ``tokens``, capped at
        ``max_tokens`` (callers cap at ``len(tokens) - 1`` so at least
        one position is left to prefill). Hit pages are ``fork``ed —
        the caller owns one reference per returned page and must
        ``free`` them (directly, or through the sequence's normal page
        lifetime). Returns ``(cached_tokens, pages)``."""
        ps = self.page_size
        toks = np.asarray(tokens).reshape(-1)
        limit = min(int(max_tokens), len(toks)) // ps
        pages: List[int] = []
        with self._lock:
            children = self._children
            for b in range(limit):
                block = tuple(int(t) for t in toks[b * ps:(b + 1) * ps])
                node = children.get(block)
                if node is None:
                    break
                self._clock += 1
                node.last_used = self._clock
                pages.append(node.page)
                children = node.children
            if pages:
                # Fork inside the lock: eviction (same scheduler
                # thread today, but the invariant should not depend on
                # that) cannot free a page between match and fork.
                self.allocator.fork(pages)
        return len(pages) * ps, pages

    def note_result(self, hit: bool) -> None:
        """Record one admission's hit/miss. Separate from ``lookup``
        deliberately: an admission that cannot get its suffix pages
        releases the fork and retries on a later scheduler pass, and
        those retries must not inflate the hit rate."""
        with self._lock:
            if hit:
                self.hits += 1
            else:
                self.misses += 1

    # -- eviction-time publication -------------------------------------------

    def insert(self, tokens: np.ndarray, pages: List[int]) -> int:
        """Publish a finished sequence's pages. ``tokens`` is the
        written token sequence (prompt + emitted tokens whose K/V was
        written); ``pages`` its page-table prefix in order. Ownership
        of EVERY page reference in ``pages`` transfers here: full-block
        pages new to the trie are kept (the sequence's reference
        becomes the cache's), duplicates of already-cached blocks and
        the partial tail page are released. Returns the number of
        newly published pages."""
        ps = self.page_size
        toks = np.asarray(tokens).reshape(-1)
        full = len(toks) // ps
        published = 0
        release: List[int] = []
        with self._lock:
            children = self._children
            parent: Optional[_Node] = None
            for b in range(min(full, len(pages))):
                block = tuple(int(t) for t in toks[b * ps:(b + 1) * ps])
                node = children.get(block)
                if node is None:
                    node = _Node(block, pages[b], parent)
                    children[block] = node
                    self._entries += 1
                    published += 1
                else:
                    # Same block already cached (possibly the very page
                    # this sequence forked at admission): release the
                    # duplicate reference, keep the canonical node.
                    release.append(pages[b])
                self._clock += 1
                node.last_used = self._clock
                children = node.children
                parent = node
            release.extend(pages[min(full, len(pages)):])
        if release:
            self.allocator.free(release)
        return published

    # -- pool pressure --------------------------------------------------------

    def evict(self, need: int) -> int:
        """Free up to ``need`` pages by dropping leaf entries in LRU
        order. Only entries whose page refcount is 1 (cache-only
        holders) are evictable — a page a live sequence forked stays.
        Returns the number of pages actually freed."""
        freed = 0
        with self._lock:
            while freed < need:
                victim = self._lru_evictable_leaf()
                if victim is None:
                    break
                self._unlink(victim)
                self.allocator.free([victim.page])
                freed += 1
        return freed

    def clear(self) -> int:
        """Release every cache-held page reference (drain/teardown, and
        the dispatch-failure path — rebuilt pools make every cached
        page's contents garbage). Pages still forked by live sequences
        just lose the cache's reference."""
        cleared = 0
        with self._lock:
            stack = list(self._children.values())
            pages: List[int] = []
            while stack:
                node = stack.pop()
                pages.append(node.page)
                stack.extend(node.children.values())
            self._children = {}
            self._entries = 0
            cleared = len(pages)
            if pages:
                self.allocator.free(pages)
        return cleared

    # -- internals (lock held) ------------------------------------------------

    def _lru_evictable_leaf(self) -> Optional[_Node]:  # holds-lock: _lock
        best: Optional[_Node] = None
        stack = list(self._children.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
                continue
            if self.allocator.refcount(node.page) != 1:
                continue               # a live sequence still maps it
            if best is None or node.last_used < best.last_used:
                best = node
        return best

    def _unlink(self, node: _Node) -> None:  # holds-lock: _lock
        siblings = (
            node.parent.children if node.parent is not None
            else self._children
        )
        siblings.pop(node.block, None)
        self._entries -= 1
