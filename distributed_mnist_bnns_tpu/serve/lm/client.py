"""Minimal stdlib client for the streaming LM server.

Shared by tests/test_lm_serve.py and scripts/lm_serve_smoke.py so both
speak the exact ndjson-over-chunked-HTTP protocol the server implements.
``http.client`` decodes chunked transfer encoding transparently, so
``readline()`` on the response yields one JSON object per emitted token
as it arrives — the incremental-streaming property the smoke asserts on
(token timestamps spread over the generation, not one burst at close).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ...obs.trace import TRACE_HEADER, format_header, mint_context


def _post(url: str, body: Dict[str, Any], timeout: float,
          headers: Optional[Dict[str, str]] = None):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    return urllib.request.urlopen(req, timeout=timeout)


def open_stream(
    base_url: str, prompt: Any, *,
    max_new_tokens: Optional[int] = None,
    deadline_ms: Optional[float] = None,
    temperature: Optional[float] = None,
    seed: Optional[int] = None,
    timeout: float = 60.0,
    trace: Any = None,
) -> Tuple[int, Any]:
    """Start a generation. Returns ``(200, response)`` — read the live
    stream with :func:`iter_lines` — or ``(code, parsed_error_body)``
    for sheds/4xx/5xx. ``trace``: the x-jg-trace contract's client
    half — ``True`` mints a context, or pass a ``TraceContext`` /
    preformatted header string for the server to adopt."""
    body: Dict[str, Any] = (
        {"text": prompt} if isinstance(prompt, str)
        else {"prompt": list(prompt)}
    )
    if max_new_tokens is not None:
        body["max_new_tokens"] = max_new_tokens
    if deadline_ms is not None:
        body["deadline_ms"] = deadline_ms
    if temperature is not None:
        body["temperature"] = temperature
    if seed is not None:
        body["seed"] = seed
    headers = None
    if trace is not None:
        if trace is True:
            trace = mint_context()
        value = trace if isinstance(trace, str) else format_header(trace)
        headers = {TRACE_HEADER: value}
    try:
        resp = _post(base_url + "/generate", body, timeout, headers)
        return resp.status, resp
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            parsed = json.loads(raw)
        except (ValueError, json.JSONDecodeError):
            parsed = {"error": raw.decode("utf-8", "replace")}
        return e.code, parsed


def iter_lines(resp) -> Iterator[Dict[str, Any]]:
    """Yield each ndjson event of a 200 stream as it arrives."""
    with resp:
        for line in resp:
            line = line.strip()
            if line:
                yield json.loads(line)


def generate(
    base_url: str, prompt: Any, **kw: Any
) -> Tuple[int, List[Dict[str, Any]]]:
    """Collect a whole generation. ``(200, [token events..., done])`` or
    ``(code, [error body])``."""
    code, resp = open_stream(base_url, prompt, **kw)
    if code != 200:
        return code, [resp]
    return code, list(iter_lines(resp))


def healthz(base_url: str, timeout: float = 10.0) -> Tuple[int, bytes]:
    try:
        with urllib.request.urlopen(
            base_url + "/healthz", timeout=timeout
        ) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def metrics(base_url: str, timeout: float = 10.0) -> Tuple[int, bytes]:
    try:
        with urllib.request.urlopen(
            base_url + "/metrics", timeout=timeout
        ) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()
