"""Minimal stdlib client for the streaming LM server.

Shared by tests/test_lm_serve.py and scripts/lm_serve_smoke.py so both
speak the exact ndjson-over-chunked-HTTP protocol the server implements.
``http.client`` decodes chunked transfer encoding transparently, so
``readline()`` on the response yields one JSON object per emitted token
as it arrives — the incremental-streaming property the smoke asserts on
(token timestamps spread over the generation, not one burst at close).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ...obs.trace import TRACE_HEADER, format_header, mint_context


def _post(url: str, body: Dict[str, Any], timeout: float,
          headers: Optional[Dict[str, str]] = None):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    return urllib.request.urlopen(req, timeout=timeout)


def open_stream(
    base_url: str, prompt: Any, *,
    max_new_tokens: Optional[int] = None,
    deadline_ms: Optional[float] = None,
    temperature: Optional[float] = None,
    seed: Optional[int] = None,
    timeout: float = 60.0,
    trace: Any = None,
) -> Tuple[int, Any]:
    """Start a generation. Returns ``(200, response)`` — read the live
    stream with :func:`iter_lines` — or ``(code, parsed_error_body)``
    for sheds/4xx/5xx. ``trace``: the x-jg-trace contract's client
    half — ``True`` mints a context, or pass a ``TraceContext`` /
    preformatted header string for the server to adopt."""
    body: Dict[str, Any] = (
        {"text": prompt} if isinstance(prompt, str)
        else {"prompt": list(prompt)}
    )
    if max_new_tokens is not None:
        body["max_new_tokens"] = max_new_tokens
    if deadline_ms is not None:
        body["deadline_ms"] = deadline_ms
    if temperature is not None:
        body["temperature"] = temperature
    if seed is not None:
        body["seed"] = seed
    headers = None
    if trace is not None:
        if trace is True:
            trace = mint_context()
        value = trace if isinstance(trace, str) else format_header(trace)
        headers = {TRACE_HEADER: value}
    try:
        resp = _post(base_url + "/generate", body, timeout, headers)
        return resp.status, resp
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            parsed = json.loads(raw)
        except (ValueError, json.JSONDecodeError):
            parsed = {"error": raw.decode("utf-8", "replace")}
        if isinstance(parsed, dict):
            # Surface the shed backoff hint (serve/ Retry-After,
            # fractional seconds) to the retry wrapper below.
            from ..client import parse_retry_after

            after = parse_retry_after(
                (e.headers or {}).get("Retry-After")
            )
            if after is not None:
                parsed["_retry_after"] = after
        return e.code, parsed


def iter_lines(resp) -> Iterator[Dict[str, Any]]:
    """Yield each ndjson event of a 200 stream as it arrives."""
    with resp:
        for line in resp:
            line = line.strip()
            if line:
                yield json.loads(line)


def generate(
    base_url: str, prompt: Any, **kw: Any
) -> Tuple[int, List[Dict[str, Any]]]:
    """Collect a whole generation. ``(200, [token events..., done])`` or
    ``(code, [error body])``."""
    code, resp = open_stream(base_url, prompt, **kw)
    if code != 200:
        return code, [resp]
    return code, list(iter_lines(resp))


def generate_with_retries(
    base_url: str, prompt: Any, *,
    max_attempts: int = 4,
    backoff_s: float = 0.05,
    seed: Optional[int] = 0,
    sleep: Any = None,
    **kw: Any,
) -> Tuple[int, List[Dict[str, Any]]]:
    """``generate`` with retry ONLY before the stream opens.

    A 503 shed (honoring the server's fractional ``Retry-After``) and a
    refused/failed connect both prove no tokens were produced — safe to
    retry, including against a fleet router that will pick another
    replica. The moment a 200 stream opens the generation is
    NON-idempotent: a mid-stream failure surfaces as the (possibly
    truncated) event list, never a silent re-generation with a
    different result."""
    import time as _time

    from ...resilience.policy import RetryPolicy

    sleep = sleep if sleep is not None else _time.sleep
    policy = RetryPolicy(
        base_backoff_s=backoff_s, max_backoff_s=1.0, seed=seed
    )
    last: Tuple[int, List[Dict[str, Any]]] = (
        599, [{"error": "no attempt made"}]
    )
    for attempt in range(1, max_attempts + 1):
        retry_after: Optional[float] = None
        try:
            code, resp = open_stream(base_url, prompt, **kw)
        except OSError as e:
            last = (-1, [{"error": f"transport: {type(e).__name__}"}])
            if attempt >= max_attempts:
                return last      # decided: don't sleep a dead delay
            sleep(policy.backoff(attempt))
            continue
        if code == 200:
            # Stream open: from here on, NEVER retry — a mid-stream
            # death surfaces as a truncated event list (the caller can
            # see exactly which tokens landed), not a silent
            # re-generation that could produce different output.
            import http.client as _http_client

            events: List[Dict[str, Any]] = []
            try:
                for ev in iter_lines(resp):
                    events.append(ev)
            except (OSError, ValueError,
                    _http_client.HTTPException) as e:
                events.append({
                    "error": f"stream failed: {type(e).__name__}",
                    "truncated": True,
                })
            if not events or not events[-1].get("done") \
                    and not events[-1].get("truncated"):
                # The ndjson protocol always ends with a done event; a
                # stream that stopped without one died mid-generation
                # (a chunked EOF is silent at this layer).
                events.append({
                    "error": "stream ended without a done event",
                    "truncated": True,
                })
            return code, events
        last = (code, [resp])
        if code != 503 or attempt >= max_attempts:
            return last
        if isinstance(resp, dict):
            retry_after = resp.get("_retry_after")
        sleep(retry_after if retry_after is not None
              else policy.backoff(attempt))
    return last


def healthz(base_url: str, timeout: float = 10.0) -> Tuple[int, bytes]:
    try:
        with urllib.request.urlopen(
            base_url + "/healthz", timeout=timeout
        ) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def metrics(base_url: str, timeout: float = 10.0) -> Tuple[int, bytes]:
    try:
        with urllib.request.urlopen(
            base_url + "/metrics", timeout=timeout
        ) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()
