"""Streaming HTTP front end for the continuous-batching LM engine.

``cli serve --lm <artifact>`` — the generation counterpart of the packed
classifier server (serve/server.py), sharing its lifecycle discipline
(bounded admission, deadlines, SIGTERM drain, obs events) but streaming
**incrementally**: tokens reach the client as they are decoded, over
chunked transfer encoding, one JSON object per line (ndjson).

  POST /generate      {"prompt": [ints] | "text": str,
                       "max_new_tokens": int, "deadline_ms": float,
                       "temperature": float, "seed": int}
                      -> 200 + ndjson stream:
                           {"i": 0, "token": 17}
                           {"i": 1, "token": 3}
                           ...
                           {"done": true, "status": "ok", "n": N}
                      503 shed (queue_full/draining/engine_failed) |
                      504 deadline before the first token |
                      400 bad input | 413 prompt too long
                      A deadline that lands MID-stream cannot change
                      the already-sent 200: the stream terminates with
                      {"done": true, "status": "deadline"} instead.
  GET  /healthz       status, active_streams, queue_depth,
                      page_occupancy, recompiles_post_warmup, kv_pool
                      HBM attribution (+ per-program costs/MFU and the
                      device-memory census when --costs is armed)
  GET  /metrics       obs registry snapshot (JSON)
  POST /admin/profile on-demand jax.profiler capture, off-path
                      (OBSERVABILITY.md "Device profiling")

Lifecycle: SIGTERM stops admission (shed ``draining``), lets active
streams run out (bounded by the drain budget), emits a ``drain`` event,
exits 0 — crash-only, same as the classifier server, and exercised by
the CI ``lm-serve-smoke`` (scripts/lm_serve_smoke.py).
"""

from __future__ import annotations

import json
import logging
import math
import queue
import threading
import time
from dataclasses import dataclass
from http.server import ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ...resilience.preempt import StopRequest
from ..httpbase import JsonHandler
from .engine import LMEngine, LMRequest

log = logging.getLogger(__name__)

# Slack granted past a deadline before the waiter gives up on the first
# token (same role as server.py's _WAIT_SLACK_S).
_WAIT_SLACK_S = 0.05

_SHED_HTTP = {"queue_full": 503, "draining": 503, "engine_failed": 503}


@dataclass
class LMServeConfig:
    """Engine geometry + robustness budgets (CLI flags mirror these)."""

    artifact: str
    host: str = "127.0.0.1"
    port: int = 8000                    # 0 = ephemeral (tests)
    slots: int = 4                      # decode batch width (compiled)
    page_size: int = 16                 # tokens per KV page
    num_pages: Optional[int] = None     # None: slots*max_pages + null
    prefill_chunk: int = 16             # prompt positions per dispatch
    max_len: Optional[int] = None       # None: the artifact's window
    queue_depth: int = 16               # admission bound
    default_deadline_ms: float = 30000.0
    default_max_new_tokens: int = 64
    max_prompt_tokens: Optional[int] = None   # None: max_len - 1
    drain_timeout_s: float = 30.0
    telemetry_dir: Optional[str] = None
    chaos: Optional[str] = None
    seed: int = 0
    interpret: Optional[bool] = None    # None: Mosaic on TPU else interp
    aot: bool = False                   # consult the AOT executable
                                        # store (aot/): hit = zero-
                                        # compile boot, fence budget 0
                                        # from BOOT; miss = compile +
                                        # re-bank
    aot_dir: Optional[str] = None       # store root (JG_AOT_STORE /
                                        # <repo>/.jax_aot default)
    trace: Optional[bool] = None        # per-request span trees in the
                                        # event log (obs/trace): None =
                                        # the JG_TRACE env var; needs
                                        # telemetry_dir
    prefix_cache: bool = False          # COW prompt-prefix sharing
                                        # over the paged pool
                                        # (SERVING.md "Prefix caching")
    spec_decode: int = 0                # self-speculative decoding
                                        # window K (0 = off): K-1
                                        # packed drafts + one fixed-K
                                        # bf16 verify dispatch per
                                        # round (SERVING.md
                                        # "Speculative decoding")
    kernels: bool = False               # Pallas serving path: in-kernel
                                        # page-table-walk attention +
                                        # fused unpack-GEMM; same
                                        # three-program set, gather
                                        # path kept as the oracle
    costs: Optional[bool] = None        # per-program HLO cost ledger +
                                        # measured MFU (obs/costs;
                                        # None = the JG_COSTS env var)
    events_max_bytes: Optional[int] = None  # size-rotate events.jsonl
                                        # (obs/events "Rotation"; None
                                        # = JG_EVENTS_MAX_BYTES, else
                                        # unbounded)


class LMServer:
    """Owns the engine, the streaming HTTP front end and the drain."""

    def __init__(self, config: LMServeConfig):
        self.config = config
        from ...obs import Telemetry
        from ...obs.costs import arm_ledger

        self.telemetry = Telemetry(
            config.telemetry_dir, heartbeat=False, trace=config.trace,
            events_max_bytes=config.events_max_bytes,
        )
        # Device introspection (obs/costs): an explicit flag wins over
        # the JG_COSTS env default; the LM engine feeds the ledger.
        self._ledger = arm_ledger(config.costs)
        from ...resilience.chaos import ChaosController

        self.chaos = ChaosController.from_config(
            config.chaos, seed=config.seed, telemetry=self.telemetry
        )
        self.stop_request = StopRequest()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._started_at = time.time()
        self.engine: Optional[LMEngine] = None
        self.artifact_info: Dict[str, Any] = {}
        self.vocab = 0
        self.aot_status: Optional[str] = None

    def _interpret(self) -> bool:
        if self.config.interpret is not None:
            return self.config.interpret
        import jax

        return jax.default_backend() != "tpu"

    def start(self) -> Tuple[str, int]:
        cfg = self.config
        from ...obs import get_tracker

        # Boot mark BEFORE the artifact load: an AOT store hit must
        # perform zero compiles from HERE (the fence baseline), not
        # merely post-warmup.
        boot_mark = get_tracker().mark()
        if cfg.aot:
            from ...aot import AotStore, load_paged_lm_decoder_aot

            decoder, info, aot_meta = load_paged_lm_decoder_aot(
                cfg.artifact,
                slots=cfg.slots,
                page_size=cfg.page_size,
                num_pages=cfg.num_pages,
                prefill_chunk=cfg.prefill_chunk,
                max_len=cfg.max_len,
                spec_k=cfg.spec_decode,
                interpret=self._interpret(),
                kernels=cfg.kernels,
                store=AotStore(cfg.aot_dir, telemetry=self.telemetry),
            )
            self.artifact_info = info
            self.aot_status = aot_meta["status"]
        else:
            from flax import serialization

            from ...infer_transformer import make_paged_lm_decoder

            with open(cfg.artifact, "rb") as f:
                frozen = serialization.msgpack_restore(f.read())
            if frozen.get("info", {}).get("kind") != "lm" and \
                    frozen.get("kind") != "lm":
                raise ValueError(
                    f"{cfg.artifact} is not a packed LM artifact"
                )
            self.artifact_info = dict(frozen.get("info", {}))
            decoder = make_paged_lm_decoder(
                frozen,
                slots=cfg.slots,
                page_size=cfg.page_size,
                num_pages=cfg.num_pages,
                prefill_chunk=cfg.prefill_chunk,
                max_len=cfg.max_len,
                spec_k=cfg.spec_decode,
                interpret=self._interpret(),
                kernels=cfg.kernels,
            )
            self.aot_status = "disabled"
        self.vocab = decoder.vocab
        self.engine = LMEngine(
            decoder,
            queue_depth=cfg.queue_depth,
            telemetry=self.telemetry,
            chaos=self.chaos if self.chaos.active else None,
            boot_compile_baseline=(
                boot_mark if self.aot_status == "hit" else None
            ),
            prefix_cache=cfg.prefix_cache,
        ).start()
        server = self

        class Handler(_LMHandler):
            srv = server

        self._httpd = ThreadingHTTPServer((cfg.host, cfg.port), Handler)
        self._httpd.daemon_threads = True
        host, port = self._httpd.server_address[:2]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="lm-serve-http",
            daemon=True,
        )
        self._http_thread.start()
        self.telemetry.manifest(
            config={
                "artifact": cfg.artifact,
                "engine": "lm",
                "slots": cfg.slots,
                "page_size": cfg.page_size,
                "num_pages": decoder.num_pages,
                "prefill_chunk": cfg.prefill_chunk,
                "max_len": decoder.max_len,
                "queue_depth": cfg.queue_depth,
                "default_deadline_ms": cfg.default_deadline_ms,
                "chaos": self.chaos.spec or None,
                "aot": self.aot_status,
                "prefix_cache": cfg.prefix_cache,
                "spec_decode": cfg.spec_decode,
                "kernels": cfg.kernels,
            },
            artifact_info=self.artifact_info,
        )
        log.info(
            "lm-serving %s on %s:%d — %d slots, %d pages x %d tokens, "
            "max_len %d", cfg.artifact, host, port, cfg.slots,
            decoder.num_pages, cfg.page_size, decoder.max_len,
        )
        return host, port

    def health(self) -> Dict[str, Any]:
        eng = self.engine
        assert eng is not None
        if eng.fence_error is not None:
            status = "failed"          # load balancers must route away
        elif eng.draining:
            status = "draining"
        else:
            status = "ok"
        health = {
            "status": status,
            "engine": "lm",
            "slots": eng.decoder.slots,
            "active_streams": eng.active_streams,
            "queue_depth": eng.queue_len,
            "pages_in_use": eng.allocator.used_count(),
            "page_occupancy": round(eng.allocator.occupancy(), 4),
            "recompiles_post_warmup": eng.recompiles_post_warmup,
            "fence_error": eng.fence_error,
            "max_len": eng.max_len,
            "kernels": bool(getattr(eng.decoder, "kernels", False)),
            "aot": self.aot_status,
            "uptime_s": round(time.time() - self._started_at, 3),
        }
        cache_stats = eng.prefix_cache_stats()
        if cache_stats is not None:
            # Prefix-cache entry count + shared-page occupancy: how
            # much of pages_in_use is the cache (reclaimable under
            # pressure), not live streams.
            health["prefix_cache_entries"] = cache_stats["entries"]
            health["shared_page_occupancy"] = (
                cache_stats["page_occupancy"]
            )
        if eng.spec_k:
            rate = eng.spec_acceptance_rate
            health["spec_k"] = eng.spec_k
            health["spec_acceptance_rate"] = (
                round(rate, 4) if rate is not None else None
            )
        # Paged-pool HBM attribution is plain arithmetic — always on.
        health["kv_pool"] = eng.kv_pool_stats()
        if self._ledger.enabled:
            # Device introspection (OBSERVABILITY.md "Device
            # profiling"): per-program costs + measured MFU, plus the
            # live HBM census (healthz is poll-rate; the CPU live-
            # buffer walk is fine here).
            from ...obs import device_memory_stats

            health["programs"] = self._ledger.snapshot()
            mem = device_memory_stats(live_fallback=True)
            if mem is not None:
                health["device_memory"] = mem
        return health

    def profile_dir_default(self) -> Optional[str]:
        """Default /admin/profile artifact dir (shared convention:
        ``<telemetry_dir>/profile``; None makes the handler require an
        explicit ``dir`` in the body)."""
        from ...obs.profile import default_capture_dir

        return default_capture_dir(self.config.telemetry_dir)

    def request_stop(self, reason: str = "stop requested") -> None:
        self.stop_request.request(reason)

    def drain_and_stop(self) -> Dict[str, Any]:
        assert self.engine is not None
        t0 = time.monotonic()
        queued = self.engine.queue_len
        streaming = self.engine.active_streams
        self.engine.begin_drain()
        flushed = self.engine.drain(timeout=self.config.drain_timeout_s)
        self.engine.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        stats = {
            "reason": self.stop_request.reason or "stop requested",
            "queued_at_drain": queued,
            "streaming_at_drain": streaming,
            "flushed": flushed,
            "requests_total": int(self.engine.requests_ctr.total()),
            "shed_total": int(self.engine.shed_ctr.total()),
            "iterations_total": self.engine.batch_seq,
            "recompiles_post_warmup": self.engine.recompiles_post_warmup,
            # After stop() the prefix cache has been cleared: every
            # page must be back in the pool — the CI smoke asserts the
            # cache was fully evictable at drain.
            "pages_in_use": self.engine.allocator.used_count(),
            "prefix_cache_entries": (
                self.engine.prefix_cache.entries
                if self.engine.prefix_cache is not None else None
            ),
            "wall_s": round(time.monotonic() - t0, 3),
        }
        if self.engine.spec_k:
            rate = self.engine.spec_acceptance_rate
            stats["spec_acceptance_rate"] = (
                round(rate, 4) if rate is not None else None
            )
        self.telemetry.emit("drain", engine="lm", **stats)
        self.telemetry.close()
        log.info("lm server drained and stopped: %s", stats)
        return stats

    def run(self) -> int:
        """CLI entry: serve until SIGTERM/SIGINT, graceful-drain, exit
        0 (the resilience/preempt.py handler pattern — handlers install
        before start() so a SIGTERM during warmup compiles still drains
        cleanly)."""
        with self.stop_request.install():
            self.start()
            while not self.stop_request.requested:
                time.sleep(0.05)
        self.drain_and_stop()
        return 0


class _LMHandler(JsonHandler):
    """Streaming per-connection handler; ``srv`` bound by subclassing.
    JSON/body-cap/timeout plumbing comes from the shared
    :class:`~..httpbase.JsonHandler`."""

    srv: LMServer
    logger = log

    def _max_body_bytes(self) -> int:
        return 1 << 22                # 4 MiB: prompts are token lists

    # -- chunked ndjson streaming --------------------------------------------

    def _start_stream(
        self, headers: Optional[Dict[str, str]] = None
    ) -> None:
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()

    def _write_line(self, obj: Dict[str, Any]) -> None:
        data = json.dumps(obj).encode() + b"\n"
        self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _end_stream(self) -> None:
        self.wfile.write(b"0\r\n\r\n")
        self.wfile.flush()

    # -- routes --------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        if self.path == "/healthz":
            self._reply(200, self.srv.health())
        elif self.path == "/metrics":
            # JSON by default, Prometheus text under Accept: text/plain
            # (shared negotiation in httpbase).
            self._reply_metrics(self.srv.telemetry.registry)
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 (http.server API)
        if self.path == "/generate":
            self._generate()
        elif self.path == "/admin/profile":
            # On-demand device capture (obs/profile; shared handler in
            # httpbase): this handler thread sleeps through the window
            # while the scheduler keeps decoding.
            self._admin_profile(
                self.srv.telemetry, self.srv.profile_dir_default()
            )
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def _parse_prompt(self, body: Dict[str, Any]) -> Optional[np.ndarray]:
        if "text" in body and "prompt" not in body:
            if not isinstance(body["text"], str) or not body["text"]:
                self._reply(400, {"error": "text must be a non-empty "
                                           "string"})
                return None
            raw = body["text"].encode("utf-8")
            return np.asarray(
                [b % self.srv.vocab for b in raw], np.int32
            )
        try:
            prompt = np.asarray(body["prompt"], np.int32)
        except (KeyError, TypeError, ValueError, OverflowError) as e:
            self._reply(400, {"error": f"bad prompt payload: {e}"})
            return None
        if prompt.ndim != 1 or prompt.shape[0] < 1:
            self._reply(400, {
                "error": f"prompt must be a non-empty 1-D token list, "
                         f"got shape {list(prompt.shape)}",
            })
            return None
        if ((prompt < 0) | (prompt >= self.srv.vocab)).any():
            self._reply(400, {
                "error": f"prompt tokens outside [0, {self.srv.vocab})",
            })
            return None
        return prompt

    def _generate(self) -> None:
        body = self._read_json()
        if body is None:
            return
        engine = self.srv.engine
        assert engine is not None
        prompt = self._parse_prompt(body)
        if prompt is None:
            return
        cfg = self.srv.config
        max_prompt = (
            cfg.max_prompt_tokens
            if cfg.max_prompt_tokens is not None else engine.max_len - 1
        )
        if prompt.shape[0] > max_prompt:
            self._reply(413, {
                "error": f"prompt of {prompt.shape[0]} tokens exceeds "
                         f"the {max_prompt}-token limit",
            })
            return
        try:
            max_new = int(body.get(
                "max_new_tokens", cfg.default_max_new_tokens
            ))
            temperature = float(body.get("temperature", 0.0))
            seed = int(body.get("seed", 0))
            deadline_ms = float(body.get(
                "deadline_ms", cfg.default_deadline_ms
            ))
        except (TypeError, ValueError) as e:
            self._reply(400, {"error": f"bad generation knob: {e}"})
            return
        if max_new < 1:
            self._reply(400, {
                "error": f"max_new_tokens must be >= 1, got {max_new}",
            })
            return
        if not (temperature >= 0):   # also catches NaN
            self._reply(400, {
                "error": f"temperature must be >= 0, got {temperature}",
            })
            return
        if seed < 0:
            self._reply(400, {
                "error": f"seed must be >= 0, got {seed}",
            })
            return
        if not (math.isfinite(deadline_ms) and deadline_ms > 0):
            self._reply(400, {
                "error": f"deadline_ms must be a positive finite "
                         f"number, got {body.get('deadline_ms')!r}",
            })
            return
        deadline = time.monotonic() + deadline_ms / 1e3
        # x-jg-trace: the client mints, this server adopts (obs/trace);
        # malformed headers degrade to a fresh trace, never a 4xx.
        from ...obs.trace import TRACE_HEADER, parse_header

        ctx = parse_header(self.headers.get(TRACE_HEADER))
        req = engine.submit(
            prompt, max_new, deadline, temperature=temperature,
            seed=seed, ctx=ctx,
        )
        if isinstance(req, str):       # shed reason
            # Retry-After rides every LM shed too: the retrying client
            # half (serve/lm/client.py) and the fleet router honor it —
            # one decode iteration is the natural turn-over hint.
            self._reply(_SHED_HTTP[req], {"error": "shed", "reason": req},
                        headers={"Retry-After": "0.100"})
            return
        self._stream_reply(req, deadline)

    def _trace_headers(self, req: LMRequest):
        from ...obs.trace import TRACE_HEADER, format_header

        ctx = req.span.context
        return {TRACE_HEADER: format_header(ctx)} if ctx else None

    def _stream_reply(self, req: LMRequest, deadline: float) -> None:
        """Wait for the first event (bounded by the deadline — a
        queued-forever request gets a clean 504 and its would-be pages
        stay free), then stream until ``done``."""
        try:
            ev = req.events.get(
                timeout=max(deadline - time.monotonic() + _WAIT_SLACK_S,
                            0.0)
            )
        except queue.Empty:
            req.cancelled = True       # scheduler drops + frees on sight
            self._reply(504, {"error": "deadline exceeded", "id": req.id},
                        headers=self._trace_headers(req))
            return
        if ev["kind"] == "done" and not req.tokens:
            # finished before emitting anything: map to a plain status
            code = {"deadline": 504, "error": 502}.get(ev["status"], 502)
            self._reply(code, {
                "error": ev.get("detail") or ev["status"], "id": req.id,
            }, headers=self._trace_headers(req))
            return
        try:
            self._start_stream(self._trace_headers(req))
            while True:
                if ev["kind"] == "done":
                    self._write_line({
                        "done": True, "status": ev["status"],
                        "n": ev["n"], "id": ev["id"],
                    })
                    break
                self._write_line({"i": ev["i"], "token": ev["token"]})
                try:
                    # Wait as long as the request's own deadline allows
                    # (the engine evicts and sends done(deadline) at
                    # expiry, so a healthy slow stream is never killed
                    # here); the +1s grace covers eviction in flight.
                    # Only a wedged engine runs this timer out.
                    ev = req.events.get(
                        timeout=max(deadline - time.monotonic(), 0.0)
                        + 1.0
                    )
                except queue.Empty:
                    # engine wedged: terminate the stream explicitly,
                    # and cancel so a recovered engine frees the slot
                    # and pages instead of decoding a ghost nobody reads
                    req.cancelled = True
                    self._write_line({
                        "done": True, "status": "error", "n": req.n_emitted,
                        "id": req.id, "detail": "stream stalled",
                    })
                    break
            self._end_stream()
        except (BrokenPipeError, ConnectionError, OSError):
            # client went away mid-stream: signal the scheduler so the
            # pages free at the next iteration instead of decoding a
            # ghost to completion
            req.cancelled = True
            self.close_connection = True
