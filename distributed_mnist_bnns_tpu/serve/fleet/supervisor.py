"""Replica supervisor: spawn/reap replica processes, autoscale them.

The serving twin of the elastic training supervisor
(resilience/elastic.py): where ``run_elastic`` turns a lost WORKER into
a remesh, this module turns a lost REPLICA into a respawn — and a
sustained load change into a membership change. The same discipline
carries over:

  * a :class:`FleetView` records the target membership (the analogue of
    ``MembershipView``): ``target`` is what the fleet should run,
    bounded by ``[min_replicas, max_replicas]``; every maintenance tick
    converges the live set toward it;
  * replica death is the COMMON case, not an incident: a dead process
    is reaped, removed from the router, and respawned with jittered
    backoff (:class:`~...resilience.policy.RetryPolicy`) so a
    crash-looping artifact cannot hot-loop the host. Respawn is cheap
    by construction — replicas boot ``--aot`` from the warm store
    (~1.7 s, zero compiles; PERF.md "Cold start"), which is exactly
    what makes autoscaling worth doing at this granularity;
  * the :class:`Autoscaler` converts sustained queue depth and shed
    rate into target changes: scale up when replicas stay saturated
    (mean queue depth past the high watermark, or the router observing
    replica sheds), scale down when the fleet stays idle. Both
    directions demand the signal hold for ``sustain_s`` (a burst is the
    micro-batcher's job, not the autoscaler's) and respect a cooldown
    between changes so the controller cannot flap. Decisions are
    pure-function-testable with injected clocks.

Scale-down retires the NEWEST live replica (LIFO): it is removed from
the router first (no new dispatches), then SIGTERM'd — its graceful
drain (serve/server.py) flushes whatever it already admitted, so a
scale-down never drops a request. See SERVING.md "Fleet".
"""

from __future__ import annotations

import http.client
import logging
import signal
import socket
import subprocess
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ...resilience.policy import RetryPolicy
from .router import HttpTransport, RouterCore

log = logging.getLogger(__name__)

AUTOSCALE_TOTAL = "fleet_autoscale_total"
RESPAWNS_TOTAL = "fleet_respawns_total"


def free_port(host: str = "127.0.0.1") -> int:
    """An ephemeral port that was free a moment ago (bind/release —
    the small race is acceptable for replica spawning: a collision
    fails the boot gate and the respawn path picks a new one)."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


@dataclass
class FleetView:
    """The supervisor's view of fleet membership (the serving analogue
    of ``resilience.elastic.MembershipView``): ``target`` is the
    replica count the maintenance loop converges toward, clamped to
    ``[min_replicas, max_replicas]``."""

    min_replicas: int
    max_replicas: int
    target: int

    def clamp(self, n: int) -> int:
        return max(self.min_replicas, min(self.max_replicas, n))


class Autoscaler:
    """Sustained-signal scale decisions, clock-injectable for tests.

    ``observe`` is called once per maintenance tick with the current
    pressure signals and returns a NEW target count or None. Scale-up
    needs ``queue_depth >= queue_high`` OR ``shed_rate > 0`` sustained
    for ``sustain_s``; scale-down needs ``queue_depth <= queue_low``
    AND zero sheds sustained. ``cooldown_s`` separates consecutive
    changes in either direction.
    """

    def __init__(
        self,
        *,
        queue_high: float = 4.0,
        queue_low: float = 0.5,
        sustain_s: float = 1.0,
        cooldown_s: float = 3.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.sustain_s = float(sustain_s)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._over_since: Optional[float] = None
        self._under_since: Optional[float] = None
        self._last_change = -float("inf")
        #: The audit record of the most recent ``observe`` call: the
        #: inputs, thresholds and cooldown/sustain state that drove the
        #: outcome. A cooldown hold used to be an invisible ``None`` —
        #: the decision event the supervisor emits is built from this.
        self.last_decision: Optional[Dict[str, Any]] = None

    def observe(
        self,
        view: FleetView,
        *,
        queue_depth: float,
        shed_rate: float,
        now: Optional[float] = None,
    ) -> Optional[int]:
        """New target or None. ``queue_depth`` is the mean replica
        admission-queue depth from the last health probes; ``shed_rate``
        is replica 503s/s observed by the router since the last tick."""
        now = self._clock() if now is None else now
        overloaded = queue_depth >= self.queue_high or shed_rate > 0
        idle = queue_depth <= self.queue_low and shed_rate == 0
        self._over_since = (
            (self._over_since if self._over_since is not None else now)
            if overloaded else None
        )
        self._under_since = (
            (self._under_since if self._under_since is not None else now)
            if idle else None
        )
        cooldown_remaining = max(
            0.0, self.cooldown_s - (now - self._last_change)
        )
        decision: Dict[str, Any] = {
            "queue_depth": round(queue_depth, 3),
            "shed_rate": round(shed_rate, 3),
            "queue_high": self.queue_high,
            "queue_low": self.queue_low,
            "sustain_s": self.sustain_s,
            "cooldown_s": self.cooldown_s,
            "cooldown_remaining_s": round(cooldown_remaining, 3),
            "over_for_s": (round(now - self._over_since, 3)
                           if self._over_since is not None else None),
            "under_for_s": (round(now - self._under_since, 3)
                            if self._under_since is not None else None),
            "target": view.target,
            "min_replicas": view.min_replicas,
            "max_replicas": view.max_replicas,
        }
        self.last_decision = decision
        if cooldown_remaining > 0:
            decision["action"] = "hold"
            decision["reason"] = (
                "cooldown" if (overloaded or idle) else "steady"
            )
            return None
        if (
            self._over_since is not None
            and now - self._over_since >= self.sustain_s
        ):
            if view.target < view.max_replicas:
                self._last_change = now
                self._over_since = None
                decision["action"] = "scale_up"
                decision["reason"] = (
                    "queue_high" if queue_depth >= self.queue_high
                    else "sheds"
                )
                return view.target + 1
            decision["action"] = "hold"
            decision["reason"] = "at_max"
            return None
        if (
            self._under_since is not None
            and now - self._under_since >= self.sustain_s
        ):
            if view.target > view.min_replicas:
                self._last_change = now
                self._under_since = None
                decision["action"] = "scale_down"
                decision["reason"] = "idle"
                return view.target - 1
            decision["action"] = "hold"
            decision["reason"] = "at_min"
            return None
        decision["action"] = "hold"
        decision["reason"] = (
            "sustaining" if (overloaded or idle) else "steady"
        )
        return None


class ReplicaMember:
    """One supervised replica process."""

    def __init__(self, rid: str, seq: int, proc: subprocess.Popen,
                 port: int, url: str, boot_deadline: float):
        self.rid = rid
        self.seq = seq                  # spawn order (LIFO retirement)
        self.proc = proc
        self.port = port
        self.url = url
        self.boot_deadline = boot_deadline
        self.state = "booting"          # booting | live | retiring


class ReplicaSupervisor:
    """Owns the replica processes behind a :class:`~.router.RouterCore`.

    ``spawn_command(rid, port, artifact)`` builds the replica's argv —
    the fleet server passes the real ``cli serve`` invocation; tests
    pass a stub server. The supervisor converges the live set toward
    ``view.target`` on every :meth:`tick` (reap → boot-gate → scale),
    which the maintenance thread runs at ``tick_interval_s``.
    """

    def __init__(
        self,
        router: RouterCore,
        spawn_command: Callable[[str, int, str], List[str]],
        *,
        artifact: str,
        view: FleetView,
        telemetry: Any = None,
        host: str = "127.0.0.1",
        boot_timeout_s: float = 120.0,
        tick_interval_s: float = 0.25,
        autoscaler: Optional[Autoscaler] = None,
        respawn_policy: Optional[RetryPolicy] = None,
        env: Optional[Dict[str, str]] = None,
        launcher: Any = None,
    ):
        self.router = router
        self.spawn_command = spawn_command
        self.artifact = artifact       # respawns/rollouts read this live
        #: Optional :class:`~.remote.RemoteLauncher`-shaped placer
        #: (``free_port``/``launch``/``ensure_artifact``/``host``): the
        #: fleet's replicas run on ITS host, artifacts shipped by
        #: digest over utils/transfer. None = local subprocesses.
        self.launcher = launcher
        self.view = view
        self.telemetry = telemetry
        self.host = host
        self.boot_timeout_s = float(boot_timeout_s)
        self.tick_interval_s = float(tick_interval_s)
        self.autoscaler = autoscaler
        self.respawn_policy = respawn_policy or RetryPolicy(
            max_restarts=1 << 30, base_backoff_s=0.2, max_backoff_s=5.0,
        )
        self.env = env
        self._members: Dict[str, ReplicaMember] = {}
        self._lock = threading.Lock()
        self._spawn_seq = 0
        self._next_spawn_at = 0.0      # respawn backoff gate
        self._consecutive_respawns = 0  # resets on a successful boot
        self._last_shed_total = 0.0
        self._last_signal_t = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.draining = False
        self._last_hold_key: Optional[tuple] = None
        reg = telemetry.registry if telemetry is not None else None
        if reg is None:
            from ...obs import default_registry

            reg = default_registry()
        self.autoscale_ctr = reg.counter(
            AUTOSCALE_TOTAL, "autoscale decisions by direction"
        )
        self.respawn_ctr = reg.counter(
            RESPAWNS_TOTAL, "replica respawns after unexpected exits"
        )

    # -- membership ----------------------------------------------------------

    def members(self) -> List[ReplicaMember]:
        with self._lock:
            return list(self._members.values())

    def live_count(self) -> int:
        return sum(1 for m in self.members() if m.state == "live")

    def _emit(self, kind: str, **fields: Any) -> None:
        if self.telemetry is not None:
            self.telemetry.emit(kind, **fields)

    def _decision(self, action: str, **fields: Any) -> None:
        """Control-plane decision audit record (OBSERVABILITY.md):
        every scale/hold/respawn/retire lands in the event log with the
        inputs that drove it, so ``cli fleet explain`` can replay why
        the fleet did what it did."""
        self._emit("decision", actor="supervisor", action=action,
                   **fields)

    def spawn_replica(self) -> ReplicaMember:
        """Launch one replica process; it joins the router only after
        its /healthz boot gate passes (``tick``). With a ``launcher``
        the process runs on the launcher's host — the spawn command is
        built against the remotely staged artifact (shipped by digest,
        zero-copy on respawn), and everything downstream (boot gate,
        probes, breakers, reap/retire signals) drives the returned
        Popen-shaped handle exactly as it would a local child."""
        with self._lock:
            self._spawn_seq += 1
            seq = self._spawn_seq
            rid = f"replica-{seq}"
        if self.launcher is not None:
            host = self.launcher.host
            port = self.launcher.free_port()
            artifact = self.launcher.ensure_artifact(self.artifact)
            proc = self.launcher.launch(
                self.spawn_command(rid, port, artifact),
                env=self.env,
            )
        else:
            host = self.host
            port = free_port(self.host)
            cmd = self.spawn_command(rid, port, self.artifact)
            proc = subprocess.Popen(cmd, env=self.env)
        member = ReplicaMember(
            rid, seq, proc, port, f"http://{host}:{port}",
            boot_deadline=time.monotonic() + self.boot_timeout_s,
        )
        with self._lock:
            self._members[rid] = member
        self._emit(
            "replica_spawn", replica=rid, port=port, pid=proc.pid,
            artifact=self.artifact,
        )
        log.info("supervisor: spawned %s (pid %d, port %d)",
                 rid, proc.pid, port)
        return member

    def _retire(self, member: ReplicaMember) -> None:
        """Graceful scale-down: unroute first, then SIGTERM — the
        replica's own drain flushes admitted work, so a scale-down
        never drops a request."""
        member.state = "retiring"
        self.router.remove_replica(member.rid)
        try:
            member.proc.send_signal(signal.SIGTERM)
        except OSError:
            pass
        self._emit("replica_exit", replica=member.rid, cause="retired",
                   pid=member.proc.pid)
        self._decision(
            "retire", replica=member.rid,
            inputs={"seq": member.seq, "target": self.view.target},
        )
        log.info("supervisor: retiring %s (scale-down)", member.rid)

    # -- boot gate -----------------------------------------------------------

    def _probe_boot(self, member: ReplicaMember) -> bool:
        """One /healthz poll of a booting replica; True when it is
        ready to route."""
        transport = HttpTransport(member.url)
        try:
            status, body, _ = transport.request(
                "GET", "/healthz", None, {}, 2.0
            )
        except (OSError, http.client.HTTPException):
            return False
        if status != 200:
            return False
        import json as _json

        try:
            health = _json.loads(body)
        except ValueError:
            return False
        return health.get("status") == "ok"

    # -- maintenance ---------------------------------------------------------

    def tick(self) -> None:
        """One maintenance pass: reap dead replicas (respawn with
        backoff), promote booted ones into the router, converge the
        live count toward ``view.target``, and consult the autoscaler."""
        now = time.monotonic()
        for member in self.members():
            rc = member.proc.poll()
            if rc is not None:
                self._reap(member, rc, now)
                continue
            if member.state == "booting":
                if self._probe_boot(member):
                    member.state = "live"
                    self._consecutive_respawns = 0
                    self.router.add_replica(
                        member.rid, HttpTransport(member.url),
                        url=member.url,
                        meta={"pid": member.proc.pid,
                              "port": member.port},
                    )
                    log.info("supervisor: %s live", member.rid)
                elif now >= member.boot_deadline:
                    log.error(
                        "supervisor: %s never became healthy within "
                        "%.0fs; killing", member.rid, self.boot_timeout_s,
                    )
                    try:
                        member.proc.kill()
                    except OSError:
                        pass
        if self.draining:
            return
        self._converge(now)
        if self.autoscaler is not None:
            self._autoscale(now)

    def _reap(self, member: ReplicaMember, rc: int, now: float) -> None:
        with self._lock:
            self._members.pop(member.rid, None)
        self.router.remove_replica(member.rid)
        if member.state == "retiring" or self.draining:
            log.info("supervisor: %s exited %d (retired)",
                     member.rid, rc)
            return
        self.respawn_ctr.inc()
        self._consecutive_respawns += 1
        delay = self.respawn_policy.backoff(self._consecutive_respawns)
        self._next_spawn_at = max(self._next_spawn_at, now + delay)
        self._emit(
            "replica_exit", replica=member.rid, cause="died", rc=rc,
            pid=member.proc.pid, respawn_backoff_s=round(delay, 3),
        )
        self._decision(
            "respawn", replica=member.rid,
            inputs={
                "rc": rc,
                "pid": member.proc.pid,
                "backoff_s": round(delay, 3),
                "consecutive_respawns": self._consecutive_respawns,
                "target": self.view.target,
            },
        )
        log.warning(
            "supervisor: %s died (rc %s) — respawning after %.2fs",
            member.rid, rc, delay,
        )

    def _converge(self, now: float) -> None:
        members = self.members()
        alive = [m for m in members if m.state != "retiring"]
        if len(alive) < self.view.target and now >= self._next_spawn_at:
            self.spawn_replica()
        elif len(alive) > self.view.target:
            live = [m for m in alive if m.state == "live"]
            if live:
                self._retire(max(live, key=lambda m: m.seq))

    def _signals(self, now: float) -> Dict[str, float]:
        """The autoscaler's inputs: mean replica queue depth from the
        router's last health probes + replica sheds/s observed by the
        router since the previous tick."""
        depths = [
            float(r.health.get("queue_depth") or 0)
            for r in self.router.replicas() if r.healthy
        ]
        queue_depth = sum(depths) / len(depths) if depths else 0.0
        shed_total = float(self.router.sheds_ctr.total())
        dt = max(now - self._last_signal_t, 1e-6)
        shed_rate = max(shed_total - self._last_shed_total, 0.0) / dt
        self._last_shed_total = shed_total
        self._last_signal_t = now
        return {"queue_depth": queue_depth, "shed_rate": shed_rate}

    def _autoscale(self, now: float) -> None:
        signals = self._signals(now)
        new_target = self.autoscaler.observe(
            self.view, queue_depth=signals["queue_depth"],
            shed_rate=signals["shed_rate"], now=now,
        )
        inputs = dict(getattr(self.autoscaler, "last_decision", None)
                      or {})
        if new_target is None:
            # A hold is a decision too — but only the pressure-driven
            # ones are worth auditing (cooldown suppressing a wanted
            # change, sustain still accumulating, bounds clamping), and
            # only on transition, not every 250 ms tick.
            reason = inputs.get("reason")
            key = (inputs.get("action"), reason)
            if reason in (None, "steady"):
                self._last_hold_key = None
            elif key != self._last_hold_key:
                self._last_hold_key = key
                self._decision("hold", inputs=inputs)
            return
        new_target = self.view.clamp(new_target)
        if new_target == self.view.target:
            return
        direction = "up" if new_target > self.view.target else "down"
        self._last_hold_key = None
        self.autoscale_ctr.inc(direction=direction)
        self._emit(
            "autoscale", direction=direction,
            target_from=self.view.target, target_to=new_target,
            queue_depth=round(signals["queue_depth"], 3),
            shed_rate=round(signals["shed_rate"], 3),
        )
        self._decision(
            f"scale_{direction}",
            inputs={**inputs, "target_to": new_target},
        )
        log.warning(
            "autoscale %s: target %d -> %d (queue_depth %.2f, "
            "shed_rate %.2f/s)", direction, self.view.target,
            new_target, signals["queue_depth"], signals["shed_rate"],
        )
        self.view.target = new_target

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ReplicaSupervisor":
        """Spawn the initial fleet and start the maintenance thread."""
        for _ in range(self.view.target):
            self.spawn_replica()

        def run() -> None:
            while not self._stop.wait(self.tick_interval_s):
                try:
                    self.tick()
                except Exception:
                    # The maintenance loop must outlive any one bad
                    # tick — a dead supervisor is an unsupervised fleet.
                    log.exception("supervisor tick failed; continuing")

        self._thread = threading.Thread(
            target=run, name="fleet-supervisor", daemon=True
        )
        self._thread.start()
        return self

    def wait_live(self, n: Optional[int] = None,
                  timeout: float = 180.0) -> bool:
        """Block until ``n`` (default: the target) replicas are live."""
        want = self.view.target if n is None else n
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.live_count() >= want:
                return True
            time.sleep(0.05)
        return False

    def drain_all(self, timeout: float = 60.0) -> Dict[str, Optional[int]]:
        """SIGTERM every replica and wait for graceful exits; returns
        {rid: returncode}. Stops the maintenance thread first so
        nothing respawns what we are stopping."""
        self.draining = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        rcs: Dict[str, Optional[int]] = {}
        for member in self.members():
            self.router.remove_replica(member.rid)
            if member.proc.poll() is None:
                try:
                    member.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + timeout
        for member in self.members():
            budget = max(deadline - time.monotonic(), 0.1)
            try:
                rcs[member.rid] = member.proc.wait(timeout=budget)
            except subprocess.TimeoutExpired:
                member.proc.kill()
                rcs[member.rid] = member.proc.wait()
                log.error(
                    "supervisor: %s did not drain in time; killed",
                    member.rid,
                )
        return rcs
