"""`cli fleet` — the multi-replica serving front end.

One process owns the whole serving fleet: the :class:`~.router.
RouterCore` (deadline-aware dispatch + health probing + per-replica
breakers), the :class:`~.supervisor.ReplicaSupervisor` (replica
subprocesses booted ``--aot`` from the warm store, respawn on death,
autoscaling between min/max), and the :class:`~.rollout.
RolloutManager` (transfer-shipped artifacts, canary, fleet-wide
rollback) — behind one stdlib HTTP endpoint:

  POST /predict        classifier requests: parsed just enough to read
                       ``deadline_ms``/``tier``, then the ORIGINAL
                       bytes are forwarded to the picked replica (the
                       bitwise reload-identity contract passes through
                       the router); failover to another replica within
                       the client deadline
  POST /generate       LM requests (``--lm`` fleets): prefix-affinity
                       pick, the replica's ndjson stream relayed
                       incrementally; no mid-stream retry
  GET  /healthz        fleet view: per-replica health/breaker/inflight
                       rows, live/target counts, current artifact
  GET  /metrics        obs registry snapshot (fleet counters + gauges);
                       Prometheus text under Accept: text/plain
  POST /admin/rollout  {"artifact": path, "ship": bool} — the rolling
                       deploy state machine (canary → promote →
                       automatic fleet-wide rollback on trip)
  POST /admin/scale    {"target": N} — manual target override, clamped
                       to [min, max] (the autoscaler keeps adjusting
                       from there unless disabled)

Lifecycle matches the single servers (crash-only, SERVING.md): SIGTERM
stops admission (503 ``draining``), SIGTERMs every replica and waits
for their graceful drains, emits one fleet ``drain`` event, exits 0.
"""

from __future__ import annotations

import http.client
import json
import logging
import math
import os
import sys
import threading
import time
from dataclasses import dataclass, field
from http.server import ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ...resilience.preempt import StopRequest
from ..core import DEFAULT_TIER, TIERS
from ..httpbase import JsonHandler
from .router import RouterCore, affinity_key
from .rollout import RolloutManager
from .supervisor import (
    Autoscaler,
    FleetView,
    ReplicaSupervisor,
)

log = logging.getLogger(__name__)


@dataclass
class FleetConfig:
    """Fleet shape + robustness budgets (CLI flags mirror these)."""

    artifact: str
    host: str = "127.0.0.1"
    port: int = 8100                 # router port; 0 = ephemeral
    replicas: int = 2                # initial target
    min_replicas: int = 1
    max_replicas: int = 4
    lm: bool = False                 # `cli serve --lm` replicas +
                                     # /generate prefix-affinity routing
    page_size: int = 16              # LM: the prefix-affinity block
    input_shape: Tuple[int, ...] = (28, 28, 1)   # rollout probe shape
    default_deadline_ms: float = 1000.0
    max_attempts: int = 3            # dispatch attempts per request
    probe_interval_s: float = 0.25   # replica /healthz poll cadence
    breaker_threshold: int = 3       # per-replica router breaker
    breaker_reset_s: float = 1.0
    boot_timeout_s: float = 180.0    # replica spawn -> healthy budget
    autoscale: bool = True
    queue_high: float = 4.0          # mean replica queue depth to grow
    queue_low: float = 0.5           # ... and to shrink below
    sustain_s: float = 1.0           # signal hold before acting
    cooldown_s: float = 3.0          # between autoscale decisions
    scrape_interval_s: float = 1.0   # replica /metrics scrape cadence
    slo: bool = True                 # burn-rate SLO alerting
    slo_fast_window_s: float = 60.0  # burn-rate fast/slow windows —
    slo_slow_window_s: float = 300.0 # smokes shrink these to seconds
    drain_timeout_s: float = 60.0
    staging_dir: Optional[str] = None   # rollout ship target (default:
                                     # <telemetry_dir>/staging)
    telemetry_dir: Optional[str] = None
    trace: Optional[bool] = None
    events_max_bytes: Optional[int] = None
    seed: int = 0
    replica_flags: List[str] = field(default_factory=list)
                                     # extra `cli serve` argv passed to
                                     # every replica (chaos, --aot,
                                     # --interpret, engine geometry...)


class FleetServer:
    """Owns router + supervisor + rollout + the HTTP front end."""

    def __init__(self, config: FleetConfig):
        self.config = config
        from ...obs import SLOMonitor, Telemetry, default_fleet_slos

        self.telemetry = Telemetry(
            config.telemetry_dir, heartbeat=False, trace=config.trace,
            events_max_bytes=config.events_max_bytes,
        )
        self.slo = SLOMonitor(
            default_fleet_slos(
                request_p99_ms=config.default_deadline_ms * 1.5,
                fast_window_s=config.slo_fast_window_s,
                slow_window_s=config.slo_slow_window_s,
            ),
            registry=self.telemetry.registry,
            emit=self.telemetry.emit,
        ) if config.slo else None
        self.router = RouterCore(
            telemetry=self.telemetry,
            probe_timeout_s=2.0,
            breaker_threshold=config.breaker_threshold,
            breaker_reset_s=config.breaker_reset_s,
            page_size=config.page_size,
            max_attempts=config.max_attempts,
            slo=self.slo,
        )
        self.view = FleetView(
            min_replicas=config.min_replicas,
            max_replicas=config.max_replicas,
            target=max(config.min_replicas,
                       min(config.replicas, config.max_replicas)),
        )
        autoscaler = Autoscaler(
            queue_high=config.queue_high,
            queue_low=config.queue_low,
            sustain_s=config.sustain_s,
            cooldown_s=config.cooldown_s,
        ) if config.autoscale else None
        self.supervisor = ReplicaSupervisor(
            self.router,
            self._spawn_command,
            artifact=config.artifact,
            view=self.view,
            telemetry=self.telemetry,
            host="127.0.0.1",
            boot_timeout_s=config.boot_timeout_s,
            autoscaler=autoscaler,
        )
        staging = config.staging_dir
        if staging is None and config.telemetry_dir:
            staging = os.path.join(config.telemetry_dir, "staging")
        probe_body = None
        if not config.lm:
            probe = np.zeros((1, *config.input_shape), np.float32)
            probe_body = json.dumps(
                {"images": probe.tolist(), "deadline_ms": 10000.0}
            ).encode()
        self.rollout = RolloutManager(
            self.router,
            artifact=config.artifact,
            supervisor=self.supervisor,
            telemetry=self.telemetry,
            staging_dir=staging,
            probe_body=probe_body,
        )
        self.stop_request = StopRequest()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._started_at = time.time()
        self.draining = False

    # -- replica command -----------------------------------------------------

    def _spawn_command(
        self, rid: str, port: int, artifact: str
    ) -> List[str]:
        cfg = self.config
        cmd = [
            sys.executable, "-m", "distributed_mnist_bnns_tpu.cli",
            "serve",
            "--artifact", artifact,
            "--host", "127.0.0.1",
            "--port", str(port),
        ]
        if cfg.lm:
            cmd.append("--lm")
            cmd += ["--page-size", str(cfg.page_size)]
        if cfg.telemetry_dir:
            cmd += [
                "--telemetry-dir",
                os.path.join(cfg.telemetry_dir, rid),
                "--log-file",
                os.path.join(cfg.telemetry_dir, f"{rid}.log"),
            ]
            if self.telemetry.tracer.enabled:
                cmd.append("--trace")
        cmd += cfg.replica_flags
        return cmd

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> Tuple[str, int]:
        cfg = self.config
        server = self

        class Handler(_FleetHandler):
            srv = server

        self._httpd = ThreadingHTTPServer((cfg.host, cfg.port), Handler)
        self._httpd.daemon_threads = True
        host, port = self._httpd.server_address[:2]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleet-http",
            daemon=True,
        )
        self._http_thread.start()
        self.supervisor.start()
        self.router.start_prober(cfg.probe_interval_s)
        if cfg.scrape_interval_s > 0:
            self.router.start_scraper(cfg.scrape_interval_s)
        self.telemetry.manifest(config={
            "artifact": cfg.artifact,
            "engine": "fleet",
            "lm": cfg.lm,
            "replicas": self.view.target,
            "min_replicas": cfg.min_replicas,
            "max_replicas": cfg.max_replicas,
            "autoscale": cfg.autoscale,
            "default_deadline_ms": cfg.default_deadline_ms,
            "replica_flags": cfg.replica_flags,
        })
        log.info(
            "fleet router on %s:%d — %d replica(s) [%d, %d], "
            "artifact %s", host, port, self.view.target,
            cfg.min_replicas, cfg.max_replicas, cfg.artifact,
        )
        return host, port

    def health(self) -> Dict[str, Any]:
        from ...obs import healthz_rollup

        snap = self.router.snapshot()
        store = self.router.metrics_store
        rollup = healthz_rollup(snap["replicas"], store.healthz())
        out = {
            "status": "draining" if self.draining else "ok",
            "engine": "fleet",
            "target_replicas": self.view.target,
            "min_replicas": self.view.min_replicas,
            "max_replicas": self.view.max_replicas,
            "artifact": self.rollout.current_artifact,
            "uptime_s": round(time.time() - self._started_at, 3),
            **snap,
            "fleet": {
                "replicas_total": rollup["replicas_total"],
                "replicas_healthy": rollup["replicas_healthy"],
                "status": rollup["status"],
                **store.status(),
            },
            "replica_health": rollup["replicas"],
        }
        if self.slo is not None:
            out["slo_open_alerts"] = self.slo.open_alerts()
        return out

    def request_stop(self, reason: str = "stop requested") -> None:
        self.stop_request.request(reason)

    def drain_and_stop(self) -> Dict[str, Any]:
        t0 = time.monotonic()
        self.draining = True        # front end replies 503 draining
        self.router.stop_scraper()
        self.router.stop_prober()
        rcs = self.supervisor.drain_all(
            timeout=self.config.drain_timeout_s
        )
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        stats = {
            "reason": self.stop_request.reason or "stop requested",
            "replica_rcs": rcs,
            "requests_total": int(self.router.requests_ctr.total()),
            "retries_total": int(self.router.retries_ctr.total()),
            "wall_s": round(time.monotonic() - t0, 3),
        }
        self.telemetry.emit("drain", engine="fleet", **stats)
        self.telemetry.close()
        log.info("fleet drained and stopped: %s", stats)
        return stats

    def run(self) -> int:
        """CLI entry: serve until SIGTERM/SIGINT, drain the whole
        fleet, exit 0 (replica exit codes folded in: a replica that
        failed its own drain fails the fleet's)."""
        with self.stop_request.install():
            self.start()
            while not self.stop_request.requested:
                time.sleep(0.05)
        stats = self.drain_and_stop()
        bad = {
            rid: rc for rid, rc in stats["replica_rcs"].items()
            if rc != 0
        }
        if bad:
            log.error("replica(s) exited non-zero at drain: %s", bad)
            return 1
        return 0


class _FleetHandler(JsonHandler):
    """Router front end. Request bodies are read RAW (one read, under
    the shared size cap) and parsed only for the routing envelope —
    the replica sees the client's exact bytes."""

    srv: FleetServer
    logger = log

    def _max_body_bytes(self) -> int:
        return 1 << 22

    def _read_raw(self) -> Optional[bytes]:
        try:
            n = int(self.headers.get("Content-Length", 0))
        except ValueError:
            self._reply(400, {"error": "bad Content-Length"})
            return None
        if n > self._max_body_bytes():
            self.close_connection = True
            self._reply(413, {"error": self._body_limit_error(n)})
            return None
        return self.rfile.read(n) if n else b"{}"

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        if self.path == "/healthz":
            self._reply(200, self.srv.health())
        elif self.path == "/metrics":
            from ...obs import FleetMetricsView

            self._reply_metrics(FleetMetricsView(
                self.srv.telemetry.registry,
                self.srv.router.metrics_store,
            ))
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 (http.server API)
        if self.path == "/predict":
            self._predict()
        elif self.path == "/generate":
            self._generate()
        elif self.path == "/admin/rollout":
            self._rollout()
        elif self.path == "/admin/scale":
            self._scale()
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    # -- routing envelope ----------------------------------------------------

    def _envelope(
        self, raw: bytes
    ) -> Optional[Tuple[Dict[str, Any], float, str]]:
        """Parse just deadline_ms + tier out of the client body (the
        rest is the replica's to validate)."""
        try:
            body = json.loads(raw or b"{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"bad request body: {e}"})
            return None
        try:
            deadline_ms = float(body.get(
                "deadline_ms", self.srv.config.default_deadline_ms
            ))
        except (TypeError, ValueError):
            deadline_ms = float("nan")
        if not (math.isfinite(deadline_ms) and deadline_ms > 0):
            self._reply(400, {
                "error": f"deadline_ms must be a positive finite "
                         f"number, got {body.get('deadline_ms')!r}",
            })
            return None
        tier = body.get("tier", DEFAULT_TIER)
        if tier not in TIERS:
            self._reply(400, {
                "error": f"unknown tier {tier!r} (have: "
                         f"{', '.join(TIERS)})",
            })
            return None
        return body, time.monotonic() + deadline_ms / 1e3, tier

    def _shed_if_draining(self) -> bool:
        if self.srv.draining:
            self._reply(503, {"error": "shed", "reason": "draining"},
                        headers={"Retry-After": "1.000"})
            return True
        return False

    def _predict(self) -> None:
        if self._shed_if_draining():
            return
        if self.srv.config.lm:
            self._reply(404, {"error": "this is an --lm fleet; "
                                       "POST /generate"})
            return
        raw = self._read_raw()
        if raw is None:
            return
        env = self._envelope(raw)
        if env is None:
            return
        _, deadline, tier = env
        from ...obs.trace import TRACE_HEADER, parse_header

        hdr = self.headers.get(TRACE_HEADER)
        status, body, rheaders = self.srv.router.dispatch_predict(
            raw, deadline=deadline,
            headers={TRACE_HEADER: hdr} if hdr else None,
            ctx=parse_header(hdr), tier=tier,
        )
        self._reply_raw(status, body, rheaders)

    def _generate(self) -> None:
        if self._shed_if_draining():
            return
        if not self.srv.config.lm:
            self._reply(404, {"error": "not an --lm fleet; "
                                       "POST /predict"})
            return
        raw = self._read_raw()
        if raw is None:
            return
        env = self._envelope(raw)
        if env is None:
            return
        body, deadline, tier = env
        key = affinity_key(
            prompt=body.get("prompt"), text=body.get("text"),
            page_size=self.srv.config.page_size,
        )
        from ...obs.trace import TRACE_HEADER, parse_header

        hdr = self.headers.get(TRACE_HEADER)
        status, payload, rheaders, _rid = (
            self.srv.router.dispatch_generate(
                raw, deadline=deadline, affinity=key,
                headers={TRACE_HEADER: hdr} if hdr else None,
                ctx=parse_header(hdr), tier=tier,
            )
        )
        if status != 200:
            self._reply_raw(status, payload, rheaders)
            return
        # relay the live ndjson stream, re-chunked to our client
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            if TRACE_HEADER in rheaders:
                self.send_header(TRACE_HEADER, rheaders[TRACE_HEADER])
            self.end_headers()
            for line in payload:
                self.wfile.write(
                    f"{len(line):X}\r\n".encode() + line + b"\r\n"
                )
                self.wfile.flush()
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (OSError, http.client.HTTPException):
            # OSError: OUR client went away. HTTPException
            # (IncompleteRead/BadStatusLine, not OSErrors): the REPLICA
            # died mid-stream — either way the chunked reply cannot be
            # terminated cleanly; drop the connection.
            self.close_connection = True
        finally:
            close = getattr(payload, "close", None)
            if close is not None:
                close()

    def _reply_raw(
        self, status: int, body: bytes, rheaders: Dict[str, str]
    ) -> None:
        """Relay a buffered replica response byte-for-byte (plus the
        pass-through headers that matter: trace id + Retry-After)."""
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k in ("x-jg-trace", "Retry-After"):
            for name, value in rheaders.items():
                if name.lower() == k.lower():
                    self.send_header(k, value)
        self.end_headers()
        self.wfile.write(body)

    # -- admin ---------------------------------------------------------------

    def _rollout(self) -> None:
        body = self._read_json()
        if body is None:
            return
        artifact = body.get("artifact")
        if not artifact:
            self._reply(400, {"error": "need {\"artifact\": path}"})
            return
        try:
            result = self.srv.rollout.rolling_reload(
                str(artifact), ship=body.get("ship"),
            )
        except (OSError, ValueError, RuntimeError) as e:
            self._reply(400, {
                "error": f"rollout failed: {type(e).__name__}: {e}",
            })
            return
        self._reply(200, result)

    def _scale(self) -> None:
        body = self._read_json()
        if body is None:
            return
        try:
            target = int(body["target"])
        except (KeyError, TypeError, ValueError):
            self._reply(400, {"error": "need {\"target\": int}"})
            return
        view = self.srv.view
        clamped = view.clamp(target)
        previous, view.target = view.target, clamped
        self.srv.telemetry.emit(
            "autoscale", direction="manual",
            target_from=previous, target_to=clamped,
        )
        self.srv.telemetry.emit(
            "decision", actor="operator", action="manual_scale",
            inputs={"requested": target, "target_to": clamped,
                    "target_from": previous,
                    "min_replicas": view.min_replicas,
                    "max_replicas": view.max_replicas},
        )
        self._reply(200, {"target": clamped, "previous": previous})
