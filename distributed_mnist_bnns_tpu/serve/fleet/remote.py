"""Remote replicas: a host agent + Popen-shaped process handles.

The :class:`~.supervisor.ReplicaSupervisor` supervises *processes* —
spawn, poll, signal, reap. Nothing in that loop actually needs the
process to be local: this module supplies the two halves that let the
same supervisor (and the same router health probes, breakers and
respawn backoff — unchanged) drive replicas on ANOTHER machine:

  :class:`HostAgent`
      a minimal control server that runs on the replica host: one
      JSON-line request per TCP connection (``spawn`` / ``poll`` /
      ``signal`` / ``free_port`` / ``ensure_artifact``), children
      tracked by pid. Artifacts are staged over the digest-verified
      :mod:`utils.transfer` framed protocol — the agent hands back a
      one-shot receive port, the client ships with ``send_file``, and
      the stored name embeds the sha256 so a respawn at the same digest
      never re-ships (the replica spec of SERVING.md "Remote fleet":
      host:port + artifact digest).

  :class:`RemoteLauncher` / :class:`RemoteProcess`
      the supervisor-side counterpart: ``launch`` returns a handle with
      the ``subprocess.Popen`` surface the supervisor already uses
      (``poll``/``wait``/``send_signal``/``kill``/``pid``), each call a
      one-line RPC. ``ensure_artifact`` stages the local artifact and
      returns its remote path for ``spawn_command``.

The agent trusts its network — it executes what it is told, exactly
like ``sshd`` with a fixed command would. Bind it to loopback or a
private interconnect; it is a fleet-internal control plane, not a
public endpoint. No module here imports jax (fleet rule: replicas do
the inference, the control plane stays light).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import signal as _signal
import socket
import subprocess
import threading
import time
from typing import Any, Dict, List, Optional

log = logging.getLogger(__name__)

_MAX_LINE = 1 << 20  # a request is one JSON line; 1 MiB is generous


def _digest(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _recv_line(conn: socket.socket) -> bytes:
    buf = bytearray()
    while not buf.endswith(b"\n"):
        if len(buf) > _MAX_LINE:
            raise IOError("request line too long")
        chunk = conn.recv(4096)
        if not chunk:
            break
        buf.extend(chunk)
    return bytes(buf)


class HostAgent:
    """The replica-host side: serve one JSON-line request per
    connection, keep the children it spawned, stage shipped artifacts
    under ``workdir/artifacts``. ``start()`` binds (port 0 picks a free
    one — read ``.port`` after), ``close()`` stops the accept loop and
    SIGKILLs any children still alive (an agent teardown must not leak
    orphan replicas)."""

    def __init__(self, workdir: str, *, host: str = "127.0.0.1",
                 port: int = 0):
        self.workdir = workdir
        self.host = host
        self.port = port
        self._srv: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._children: Dict[int, subprocess.Popen] = {}
        self._lock = threading.Lock()
        self._closing = False

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "HostAgent":
        os.makedirs(os.path.join(self.workdir, "artifacts"), exist_ok=True)
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self.host, self.port))
        srv.listen(16)
        # closing the fd does not wake a thread parked in accept() on
        # Linux — poll so close() returns promptly
        srv.settimeout(0.2)
        self.port = srv.getsockname()[1]
        self._srv = srv
        self._thread = threading.Thread(
            target=self._serve, name="fleet-host-agent", daemon=True
        )
        self._thread.start()
        log.info("host agent serving on %s:%d (workdir %s)",
                 self.host, self.port, self.workdir)
        return self

    def close(self) -> None:
        self._closing = True
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        with self._lock:
            children = list(self._children.values())
        for proc in children:
            if proc.poll() is None:
                try:
                    proc.kill()
                except OSError:
                    pass
                proc.wait()

    # -- serving -------------------------------------------------------------

    def _serve(self) -> None:
        assert self._srv is not None
        while not self._closing:
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # closed
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn: socket.socket) -> None:
        with conn:
            conn.settimeout(30.0)
            try:
                req = json.loads(_recv_line(conn).decode() or "{}")
                resp = self._dispatch(req)
            except Exception as e:  # a bad request must not kill the agent
                log.warning("host agent request failed: %s", e)
                resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
            try:
                conn.sendall(json.dumps(resp).encode() + b"\n")
            except OSError:
                pass

    def _dispatch(self, req: Dict[str, Any]) -> Dict[str, Any]:
        op = req.get("op")
        if op == "spawn":
            return self._op_spawn(req)
        if op == "poll":
            return self._op_poll(req)
        if op == "signal":
            return self._op_signal(req)
        if op == "free_port":
            with socket.socket() as s:
                s.bind((self.host, 0))
                return {"ok": True, "port": s.getsockname()[1]}
        if op == "ensure_artifact":
            return self._op_ensure_artifact(req)
        if op == "ping":
            return {"ok": True, "pid": os.getpid()}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def _op_spawn(self, req: Dict[str, Any]) -> Dict[str, Any]:
        cmd = req["cmd"]
        env = dict(os.environ)
        env.update(req.get("env") or {})
        proc = subprocess.Popen(
            [str(c) for c in cmd], env=env, cwd=self.workdir
        )
        with self._lock:
            self._children[proc.pid] = proc
        log.info("host agent spawned pid %d: %s", proc.pid, cmd)
        return {"ok": True, "pid": proc.pid}

    def _op_poll(self, req: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            proc = self._children.get(int(req["pid"]))
        if proc is None:
            return {"ok": False, "error": f"unknown pid {req.get('pid')}"}
        return {"ok": True, "rc": proc.poll()}

    def _op_signal(self, req: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            proc = self._children.get(int(req["pid"]))
        if proc is None:
            return {"ok": False, "error": f"unknown pid {req.get('pid')}"}
        try:
            proc.send_signal(int(req.get("signum") or _signal.SIGTERM))
        except OSError as e:
            return {"ok": False, "error": str(e)}
        return {"ok": True}

    def _op_ensure_artifact(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """Stage-by-digest: the stored name embeds the sha256, so the
        common respawn/rollback case (same digest) answers from disk
        with zero bytes shipped. A miss hands back a one-shot
        :func:`utils.transfer.receive_file` port; the framed protocol
        verifies the digest before the atomic rename, and we re-check
        it against the digest the CLIENT promised (a sender shipping
        the wrong-but-intact file is rejected here)."""
        name = os.path.basename(str(req["name"]))
        sha = str(req["sha256"])
        dest = os.path.join(
            self.workdir, "artifacts", f"{sha[:16]}-{name}"
        )
        if os.path.exists(dest):
            return {"ok": True, "path": dest, "shipped": False}
        if req.get("probe"):
            # A staging poll: report not-yet-there without opening
            # another one-shot receive port.
            return {"ok": True, "path": dest, "shipped": True}
        with socket.socket() as s:
            s.bind((self.host, 0))
            port = s.getsockname()[1]

        result: Dict[str, Any] = {}

        def _receive() -> None:
            from ...utils.transfer import receive_file

            try:
                result["path"], _ = receive_file(
                    os.path.join(self.workdir, "artifacts"), port,
                    host=self.host, timeout=120.0,
                )
            except Exception as e:
                result["error"] = f"{type(e).__name__}: {e}"

        t = threading.Thread(target=_receive, daemon=True)
        t.start()
        # The client ships on seeing this response; finalize in a
        # follow-up thread so the one-line RPC can return now.

        def _finalize() -> None:
            t.join(timeout=130.0)
            path = result.get("path")
            if not path:
                log.warning("artifact ship to port %d failed: %s",
                            port, result.get("error", "timeout"))
                return
            if _digest(path) != sha:
                log.warning(
                    "shipped artifact digest mismatch (want %s…); "
                    "discarding", sha[:16],
                )
                os.remove(path)
                return
            os.replace(path, dest)
            log.info("staged artifact %s", dest)

        threading.Thread(target=_finalize, daemon=True).start()
        return {"ok": True, "path": dest, "shipped": True, "port": port}


class RemoteProcess:
    """A ``subprocess.Popen``-shaped handle for an agent-spawned
    process — exactly the surface the supervisor's reap/retire/drain
    paths use. An agent that became unreachable reads as exit
    ``-SIGKILL``: the host is gone, and the supervisor's host-loss
    handling (respawn with backoff) is precisely the right response."""

    def __init__(self, launcher: "RemoteLauncher", pid: int):
        self._launcher = launcher
        self.pid = pid
        self.returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        try:
            resp = self._launcher._rpc({"op": "poll", "pid": self.pid})
        except (OSError, ValueError):
            self.returncode = -int(_signal.SIGKILL)
            return self.returncode
        if not resp.get("ok"):
            self.returncode = -int(_signal.SIGKILL)
        elif resp.get("rc") is not None:
            self.returncode = int(resp["rc"])
        return self.returncode

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired(
                    f"remote pid {self.pid}", timeout
                )
            time.sleep(0.05)
        return self.returncode  # type: ignore[return-value]

    def send_signal(self, signum: int) -> None:
        if self.returncode is not None:
            return
        try:
            self._launcher._rpc(
                {"op": "signal", "pid": self.pid, "signum": int(signum)}
            )
        except (OSError, ValueError):
            pass  # same contract as Popen.send_signal on a dead child

    def terminate(self) -> None:
        self.send_signal(int(_signal.SIGTERM))

    def kill(self) -> None:
        self.send_signal(int(_signal.SIGKILL))


class RemoteLauncher:
    """The supervisor-side client of one :class:`HostAgent` — pass as
    ``ReplicaSupervisor(..., launcher=...)`` to place that fleet's
    replicas on the agent's host. ``host`` is where spawned replicas
    are reachable (the router's transport URLs are built from it)."""

    def __init__(self, host: str, port: int, *, timeout_s: float = 10.0):
        self.host = host
        self.port = port
        self.timeout_s = float(timeout_s)
        self._digests: Dict[str, str] = {}   # local path -> sha256
        self._staged: Dict[str, str] = {}    # sha256 -> remote path

    def _rpc(self, req: Dict[str, Any]) -> Dict[str, Any]:
        with socket.create_connection(
            (self.host, self.port), timeout=self.timeout_s
        ) as s:
            s.sendall(json.dumps(req).encode() + b"\n")
            s.shutdown(socket.SHUT_WR)
            buf = bytearray()
            while not buf.endswith(b"\n"):
                chunk = s.recv(4096)
                if not chunk:
                    break
                buf.extend(chunk)
        return json.loads(buf.decode() or "{}")

    def ping(self) -> bool:
        try:
            return bool(self._rpc({"op": "ping"}).get("ok"))
        except (OSError, ValueError):
            return False

    def free_port(self) -> int:
        resp = self._rpc({"op": "free_port"})
        if not resp.get("ok"):
            raise IOError(f"agent free_port failed: {resp.get('error')}")
        return int(resp["port"])

    def launch(self, cmd: List[str],
               env: Optional[Dict[str, str]] = None) -> RemoteProcess:
        resp = self._rpc({"op": "spawn", "cmd": list(cmd),
                          "env": dict(env or {})})
        if not resp.get("ok"):
            raise IOError(f"agent spawn failed: {resp.get('error')}")
        return RemoteProcess(self, int(resp["pid"]))

    def ensure_artifact(self, path: str) -> str:
        """The local artifact's path ON THE AGENT HOST, shipping it
        (utils/transfer, digest-verified) only if that digest is not
        already staged there. Respawns and rollbacks re-resolve through
        here, so they are zero-copy at an unchanged digest."""
        sha = self._digests.get(path)
        if sha is None:
            sha = self._digests[path] = _digest(path)
        cached = self._staged.get(sha)
        if cached is not None:
            return cached
        resp = self._rpc({
            "op": "ensure_artifact",
            "name": os.path.basename(path), "sha256": sha,
        })
        if not resp.get("ok"):
            raise IOError(
                f"agent ensure_artifact failed: {resp.get('error')}"
            )
        if resp.get("shipped"):
            from ...utils.transfer import send_file

            send_file(path, self.host, int(resp["port"]))
            # The agent finalizes (digest re-check + atomic rename) off
            # the RPC path; re-ask until it answers from disk so a
            # spawn_command never names an artifact that is not staged
            # yet.
            deadline = time.monotonic() + 30.0
            while True:
                check = self._rpc({
                    "op": "ensure_artifact", "probe": True,
                    "name": os.path.basename(path), "sha256": sha,
                })
                if check.get("ok") and not check.get("shipped"):
                    break
                if time.monotonic() > deadline:
                    raise IOError(
                        f"artifact {path} shipped but never staged "
                        f"(digest {sha[:16]}…)"
                    )
                time.sleep(0.05)
        self._staged[sha] = str(resp["path"])
        return self._staged[sha]
