"""Fleet router: deadline-aware least-loaded dispatch over N replicas.

The Tail-at-Scale argument (Dean & Barroso) is that tail tolerance must
come from the FLEET layer — no single replica, however hardened, can
hide its own stall. This module is that layer for the serving tier:

  * **health-aware membership** — a prober polls every replica's
    ``/healthz``; a replica reporting ``failed`` (recompile fence
    tripped — ``fence_error``), ``draining``, or not answering at all
    is ejected from dispatch until a later probe readmits it. Every
    transition lands as a ``replica_health`` event.
  * **per-replica circuit breaker** — transport errors and 5xx
    responses feed a :class:`~...resilience.policy.CircuitBreaker` per
    replica, so a replica that answers probes but fails requests is
    ejected too (and re-enters through the breaker's half-open probe).
  * **deadline-aware dispatch** — a request whose deadline has already
    passed fails fast with NO dispatch; the per-attempt transport
    timeout is the request's remaining budget, never a fixed number.
  * **retry-on-another-replica** — idempotent requests (classifier
    ``/predict``) that fail on one replica retry on a different one
    while the client's deadline allows; replica sheds (503) also fail
    over, because another replica's queue may have room. LM
    ``/generate`` only fails over BEFORE its stream opens (a 503 shed
    or a refused connect proves no tokens were produced); once tokens
    flow the generation is non-idempotent and is never retried.
  * **prefix-affinity routing** — LM requests hash the FIRST page-size
    block of the prompt and rendezvous-hash it over the live replicas,
    so requests sharing a prompt prefix (system prompts) land on the
    replica whose prefix cache is warm (SERVING.md "Prefix caching").
    Rendezvous hashing keeps the mapping stable under membership
    churn: a replica joining or leaving only remaps the keys it owns.
  * **one trace per hop chain** — an incoming ``x-jg-trace`` header is
    forwarded UNCHANGED, so the client's span tree, the router's
    ``fleet.request``/``fleet.dispatch`` spans and the replica's
    ``serve.request`` tree all join on one trace id; an untraced client
    gets a router-minted context forwarded downstream instead.

Transport is pluggable: :class:`HttpTransport` speaks to real replica
processes; tests and the availability harness plug in-process
callables, so the dispatch policy is unit-testable with fake clocks
and no sockets. See SERVING.md "Fleet".
"""

from __future__ import annotations

import hashlib
import http.client
import json
import logging
import threading
import time
import urllib.parse
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ...obs.aggregate import FleetMetricsStore
from ...obs.trace import (
    NULL_TRACER,
    TRACE_HEADER,
    TraceContext,
    format_header,
)
from ...resilience.policy import CircuitBreaker
from ..core import DEFAULT_TIER

log = logging.getLogger(__name__)

FLEET_REQUESTS_TOTAL = "fleet_requests_total"
FLEET_RETRIES_TOTAL = "fleet_retries_total"
FLEET_DISPATCH_TOTAL = "fleet_dispatch_total"
FLEET_SHEDS_TOTAL = "fleet_sheds_observed_total"
REPLICAS_GAUGE = "fleet_replicas"
REPLICAS_HEALTHY_GAUGE = "fleet_replicas_healthy"

# Extra transport slack past the client deadline: covers response
# serialization on the replica side (mirrors server.py's wait slack).
_DISPATCH_SLACK_S = 0.1

# Retry-After on router-level sheds (no healthy replica): one probe
# interval is when membership can next change.
_NO_REPLICA_RETRY_AFTER_S = 0.25


class HttpTransport:
    """stdlib transport to one replica. ``request`` buffers the whole
    response; ``stream`` hands back the live ``HTTPResponse`` for
    ndjson relaying (http.client undoes the chunked encoding)."""

    def __init__(self, base_url: str):
        self.base_url = base_url.rstrip("/")
        parsed = urllib.parse.urlsplit(self.base_url)
        self._host = parsed.hostname or "127.0.0.1"
        self._port = parsed.port or 80

    def _connect(self, timeout: float) -> http.client.HTTPConnection:
        return http.client.HTTPConnection(
            self._host, self._port, timeout=max(timeout, 0.001)
        )

    def request(
        self, method: str, path: str, body: Optional[bytes],
        headers: Dict[str, str], timeout: float,
    ) -> Tuple[int, bytes, Dict[str, str]]:
        conn = self._connect(timeout)
        try:
            conn.request(
                method, path, body=body,
                headers={
                    **({"Content-Type": "application/json"} if body
                       else {}),
                    **headers,
                },
            )
            resp = conn.getresponse()
            return resp.status, resp.read(), dict(resp.headers)
        finally:
            conn.close()

    def stream(
        self, path: str, body: Optional[bytes],
        headers: Dict[str, str], timeout: float,
    ):
        """``(status, payload, headers)``; on 200 ``payload`` is a
        ``close()``-able iterator of ndjson lines (the live response),
        else the buffered error body bytes."""
        conn = self._connect(timeout)
        try:
            conn.request(
                "POST", path, body=body,
                headers={
                    **({"Content-Type": "application/json"} if body
                       else {}),
                    **headers,
                },
            )
            resp = conn.getresponse()
            if resp.status != 200:
                try:
                    return resp.status, resp.read(), dict(resp.headers)
                finally:
                    conn.close()
            return resp.status, _LiveStream(conn, resp), \
                dict(resp.headers)
        except BaseException:
            conn.close()
            raise


class _LiveStream:
    """Line iterator over a streaming replica response that closes its
    connection when done (or abandoned)."""

    def __init__(self, conn: http.client.HTTPConnection, resp: Any):
        self._conn = conn
        self._resp = resp

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        line = self._resp.readline()
        if not line:
            self.close()
            raise StopIteration
        return line

    def close(self) -> None:
        try:
            self._conn.close()
        except OSError:
            pass


class Replica:
    """Router-side state for one backend replica."""

    def __init__(
        self,
        rid: str,
        transport: Any,
        *,
        url: str = "",
        breaker: Optional[CircuitBreaker] = None,
        clock: Callable[[], float] = time.monotonic,
        meta: Optional[Dict[str, Any]] = None,
    ):
        self.rid = rid
        self.transport = transport
        self.url = url
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            failure_threshold=3, reset_timeout_s=1.0,
        )
        self.meta: Dict[str, Any] = dict(meta or {})
        self.health: Dict[str, Any] = {}
        self.healthy = True           # optimistic until a probe says no
        self.transitions: List[Dict[str, Any]] = []
        self._clock = clock
        self._lock = threading.Lock()
        self._inflight = 0
        self.seq = 0                  # registration order (tie-break)

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def _enter(self) -> None:
        with self._lock:
            self._inflight += 1

    def _exit(self) -> None:
        with self._lock:
            self._inflight -= 1

    def note_transition(self, what: str, reason: str) -> None:
        self.transitions.append({
            "t": round(self._clock(), 4), "to": what, "reason": reason,
        })

    def snapshot(self) -> Dict[str, Any]:
        """The /healthz row for this replica."""
        return {
            "id": self.rid,
            "url": self.url,
            "healthy": self.healthy,
            "breaker": self.breaker.state,
            "inflight": self.inflight,
            "status": self.health.get("status"),
            "queue_depth": self.health.get("queue_depth"),
            "aot": self.health.get("aot"),
            "recompiles_post_boot": self.health.get(
                "recompiles_post_boot",
                self.health.get("recompiles_post_warmup"),
            ),
            "fence_error": self.health.get("fence_error"),
            **self.meta,
        }


def affinity_key(
    prompt: Any = None, text: Optional[str] = None, *, page_size: int = 16
) -> Optional[str]:
    """The prefix-affinity contract (SERVING.md "Fleet"): hash ONLY the
    first page-size block of the prompt — the largest unit the prefix
    cache shares whole — so every request with the same leading block
    (system prompt) maps to the same replica, and requests differing
    anywhere in that block spread. Sub-block prompts return None (no
    full page to share — least-loaded is the better policy)."""
    if text is not None:
        raw = text.encode("utf-8")
        if len(raw) < page_size:
            return None
        block = raw[:page_size]
    elif prompt is not None:
        toks = list(prompt)
        if len(toks) < page_size:
            return None
        block = json.dumps(toks[:page_size]).encode()
    else:
        return None
    return hashlib.sha1(block).hexdigest()


def _rewrite_deadline(body: bytes, remaining_ms: float) -> bytes:
    """Re-encode a request body with ``deadline_ms`` set to the
    remaining budget (failover attempts must never forward the
    original, already-part-spent deadline). Unparseable bodies pass
    through untouched — the replica will 400 them itself."""
    try:
        obj = json.loads(body or b"{}")
        if not isinstance(obj, dict):
            return body
    except ValueError:
        return body
    obj["deadline_ms"] = max(round(remaining_ms, 3), 1.0)
    return json.dumps(obj).encode()


class _CountedStream:
    """Wraps a live generate stream so the owning replica's in-flight
    count (the least-loaded signal) covers the stream's whole lifetime,
    not just the dispatch call; decrements exactly once."""

    def __init__(self, inner: Any, on_close: Callable[[], None]):
        self._inner = inner
        self._on_close = on_close
        self._open = True

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._inner)
        except BaseException:
            self.close()
            raise

    def close(self) -> None:
        if self._open:
            self._open = False
            self._on_close()
        close = getattr(self._inner, "close", None)
        if close is not None:
            close()


def _rendezvous_score(key: str, rid: str) -> int:
    return int.from_bytes(
        hashlib.sha1(f"{key}|{rid}".encode()).digest()[:8], "big"
    )


class RouterCore:
    """The dispatch policy, transport-agnostic (no HTTP front end —
    :class:`~.server.FleetServer` adds that). Thread-safe: handler
    threads dispatch concurrently while the prober and the supervisor
    mutate membership."""

    def __init__(
        self,
        *,
        telemetry: Any = None,
        clock: Callable[[], float] = time.monotonic,
        probe_timeout_s: float = 2.0,
        breaker_threshold: int = 3,
        breaker_reset_s: float = 1.0,
        page_size: int = 16,
        max_attempts: int = 3,
        slo: Any = None,
    ):
        self.telemetry = telemetry
        self.tracer = getattr(telemetry, "tracer", None) or NULL_TRACER
        self._clock = clock
        self.probe_timeout_s = float(probe_timeout_s)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_reset_s = float(breaker_reset_s)
        self.page_size = int(page_size)
        self.max_attempts = int(max_attempts)
        self.slo = slo               # obs.slo.SLOMonitor (optional)
        self.metrics_store = FleetMetricsStore(clock=clock)
        self._lock = threading.Lock()
        self._replicas: Dict[str, Replica] = {}
        self._seq = 0
        self._prober: Optional[threading.Thread] = None
        self._scraper: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._stop_scrape = threading.Event()
        reg = telemetry.registry if telemetry is not None else None
        if reg is None:
            from ...obs import default_registry

            reg = default_registry()
        self.requests_ctr = reg.counter(
            FLEET_REQUESTS_TOTAL, "router requests by final status"
        )
        self.retries_ctr = reg.counter(
            FLEET_RETRIES_TOTAL, "failover retries by cause"
        )
        self.sheds_ctr = reg.counter(
            FLEET_SHEDS_TOTAL,
            "replica-side 503 sheds seen by the router (the "
            "autoscaler's scale-up pressure signal)",
        )
        self.dispatch_ctr = reg.counter(
            FLEET_DISPATCH_TOTAL, "dispatches per replica"
        )
        self.replicas_gauge = reg.gauge(
            REPLICAS_GAUGE, "registered replicas"
        )
        self.healthy_gauge = reg.gauge(
            REPLICAS_HEALTHY_GAUGE, "replicas currently routable"
        )

    # -- membership ----------------------------------------------------------

    def add_replica(
        self, rid: str, transport: Any, *, url: str = "",
        meta: Optional[Dict[str, Any]] = None,
    ) -> Replica:
        replica = Replica(
            rid, transport, url=url, clock=self._clock, meta=meta,
            breaker=CircuitBreaker(
                failure_threshold=self.breaker_threshold,
                reset_timeout_s=self.breaker_reset_s,
                clock=self._clock,
                on_transition=self._breaker_transition(rid),
            ),
        )
        with self._lock:
            self._seq += 1
            replica.seq = self._seq
            self._replicas[rid] = replica
        self._gauges()
        log.info("router: replica %s registered (%s)", rid, url or "local")
        return replica

    def remove_replica(self, rid: str) -> Optional[Replica]:
        with self._lock:
            replica = self._replicas.pop(rid, None)
        self._gauges()
        self.metrics_store.discard(rid)
        if replica is not None:
            log.info("router: replica %s removed", rid)
        return replica

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def get_replica(self, rid: str) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(rid)

    def _gauges(self) -> None:
        reps = self.replicas()
        self.replicas_gauge.set(len(reps))
        self.healthy_gauge.set(sum(1 for r in reps if r.healthy))

    def _breaker_transition(self, rid: str):
        def on_transition(old: str, new: str, reason: str) -> None:
            if self.telemetry is not None:
                self.telemetry.emit(
                    "replica_health", replica=rid, breaker=new,
                    breaker_from=old, reason=reason,
                )
            self._decision(
                f"breaker_{new}", replica=rid,
                inputs={
                    "from": old,
                    "reason": reason,
                    "failure_threshold": self.breaker_threshold,
                    "reset_timeout_s": self.breaker_reset_s,
                },
            )
            replica = self.get_replica(rid)
            if replica is not None:
                replica.note_transition(f"breaker_{new}", reason)
        return on_transition

    def _decision(self, action: str, **fields: Any) -> None:
        """The control-plane audit record: WHAT the router decided and
        the inputs that drove it (OBSERVABILITY.md `decision` kind —
        `cli fleet explain` renders the timeline)."""
        if self.telemetry is not None:
            self.telemetry.emit(
                "decision", actor="router", action=action, **fields
            )

    # -- health probing ------------------------------------------------------

    def probe_replicas(self) -> None:
        """One probe pass over the registered replicas (the prober
        thread loops this; tests call it directly)."""
        for replica in self.replicas():
            try:
                status, body, _ = replica.transport.request(
                    "GET", "/healthz", None, {}, self.probe_timeout_s
                )
                health = json.loads(body)
            except (OSError, ValueError,
                    http.client.HTTPException) as e:
                self._mark(
                    replica, False,
                    f"probe_error:{type(e).__name__}",
                )
                continue
            replica.health = health
            if status != 200:
                self._mark(replica, False, f"http_{status}")
            elif health.get("fence_error"):
                # The replica's recompile fence tripped: it answers
                # probes but sheds everything — route away NOW.
                self._mark(replica, False, "fence_error")
            elif health.get("status") != "ok":
                self._mark(replica, False, str(health.get("status")))
            else:
                self._mark(replica, True, "ok")

    def _mark(self, replica: Replica, healthy: bool, reason: str) -> None:
        if replica.healthy == healthy:
            return
        replica.healthy = healthy
        replica.note_transition("healthy" if healthy else "ejected",
                                reason)
        self._gauges()
        if self.telemetry is not None:
            self.telemetry.emit(
                "replica_health", replica=replica.rid,
                healthy=healthy, reason=reason,
            )
        self._decision(
            "readmit" if healthy else "eject", replica=replica.rid,
            inputs={
                "reason": reason,
                "probe_status": replica.health.get("status"),
                "queue_depth": replica.health.get("queue_depth"),
                "breaker": replica.breaker.state,
                "inflight": replica.inflight,
            },
        )
        log.warning(
            "router: replica %s %s (%s)", replica.rid,
            "healthy" if healthy else "EJECTED", reason,
        )

    def start_prober(self, interval_s: float = 0.25) -> None:
        self._stop.clear()

        def run() -> None:
            while not self._stop.wait(interval_s):
                self.probe_replicas()
                # Burn rates re-evaluate on the probe cadence: the same
                # clock tick that can change membership can open/close
                # an slo_alert (obs/slo.py).
                if self.slo is not None:
                    self.slo.evaluate()

        self._prober = threading.Thread(
            target=run, name="fleet-prober", daemon=True
        )
        self._prober.start()

    def stop_prober(self) -> None:
        self._stop.set()
        if self._prober is not None:
            self._prober.join(timeout=5.0)
            self._prober = None

    # -- metrics scraping ----------------------------------------------------

    def scrape_replicas(self) -> None:
        """One scrape pass: pull every registered replica's ``/metrics``
        (the registry-snapshot JSON) into :attr:`metrics_store`, which
        the fleet-merged ``/metrics`` endpoint folds with the router's
        own registry (obs/aggregate.py). The `/healthz` half reuses the
        probe plumbing — the prober already banks each replica's latest
        health body on ``replica.health``."""
        for replica in self.replicas():
            try:
                status, body, _ = replica.transport.request(
                    "GET", "/metrics", None, {}, self.probe_timeout_s
                )
                snapshot = json.loads(body) if status == 200 else None
            except (OSError, ValueError,
                    http.client.HTTPException) as e:
                self.metrics_store.update(
                    replica.rid,
                    error=f"{type(e).__name__}: {e}",
                )
                continue
            if not isinstance(snapshot, dict):
                self.metrics_store.update(
                    replica.rid, error=f"http_{status}"
                )
                continue
            self.metrics_store.update(
                replica.rid, snapshot=snapshot,
                healthz=dict(replica.health),
            )

    def start_scraper(self, interval_s: float = 1.0) -> None:
        self._stop_scrape.clear()

        def run() -> None:
            while not self._stop_scrape.wait(interval_s):
                self.scrape_replicas()

        self._scraper = threading.Thread(
            target=run, name="fleet-scraper", daemon=True
        )
        self._scraper.start()

    def stop_scraper(self) -> None:
        self._stop_scrape.set()
        if self._scraper is not None:
            self._scraper.join(timeout=5.0)
            self._scraper = None

    # -- dispatch ------------------------------------------------------------

    def pick(
        self, *, exclude: Iterable[str] = (),
        affinity: Optional[str] = None,
    ) -> Optional[Replica]:
        """Routable replica for one attempt: healthy, breaker admitting,
        not already tried. With an affinity key: the rendezvous-hash
        winner among the candidates (stable under membership churn);
        otherwise least-loaded (fewest in-flight dispatches, oldest
        registration breaking ties)."""
        excluded = set(exclude)
        candidates = [
            r for r in self.replicas()
            if r.healthy and r.rid not in excluded and r.breaker.admits()
        ]
        if not candidates:
            return None
        if affinity is not None:
            return max(
                candidates,
                key=lambda r: _rendezvous_score(affinity, r.rid),
            )
        return min(candidates, key=lambda r: (r.inflight, r.seq))

    def _forward_headers(
        self, headers: Optional[Dict[str, str]],
        ctx: Optional[TraceContext], root: Any,
    ) -> Dict[str, str]:
        """The x-jg-trace contract: an incoming header is forwarded
        UNCHANGED; an untraced client gets the router's own context so
        the replica still joins the router's trace."""
        out = dict(headers or {})
        if TRACE_HEADER not in out:
            fwd = ctx or getattr(root, "context", None)
            if fwd:
                out[TRACE_HEADER] = format_header(fwd)
        return out

    def dispatch_predict(
        self,
        body: bytes,
        *,
        deadline: float,
        headers: Optional[Dict[str, str]] = None,
        ctx: Optional[TraceContext] = None,
        tier: str = DEFAULT_TIER,
    ) -> Tuple[int, bytes, Dict[str, str]]:
        """Route one idempotent ``/predict`` request: least-loaded
        dispatch, failover to ANOTHER replica on transport error / 5xx
        / replica shed while the deadline allows. Returns ``(status,
        body_bytes, response_headers)`` — the replica's bytes untouched
        on success (the rolling-reload bitwise-identity contract passes
        through the router)."""
        t0 = self._clock()
        root = self.tracer.start(
            "fleet.request", kind="request", ctx=ctx, fresh=True,
            tier=tier,
        )
        fwd_headers = self._forward_headers(headers, ctx, root)
        tried: List[str] = []
        attempts = 0
        last: Tuple[int, bytes, Dict[str, str]] = (
            503,
            json.dumps({"error": "shed", "reason": "no_replica"}).encode(),
            {"Retry-After": f"{_NO_REPLICA_RETRY_AFTER_S:.3f}"},
        )
        send_body = body
        while True:
            now = self._clock()
            if now >= deadline:
                # Deadline-expired fail-fast: never dispatch work the
                # client has already given up on.
                out = json.dumps({
                    "error": "deadline exceeded at router",
                    "retries": attempts,
                }).encode()
                self._done(root, t0, "deadline", None, attempts, tier)
                return 504, out, {}
            if attempts >= self.max_attempts:
                self._done(root, t0, f"gave_up_{last[0]}", None,
                           attempts, tier)
                return last
            replica = self.pick(exclude=tried)
            if replica is None or not replica.breaker.allow():
                if replica is not None:
                    # half-open with its probe budget spent this tick
                    tried.append(replica.rid)
                    continue
                self._done(root, t0, "no_replica", None, attempts, tier)
                return last
            attempts += 1
            self.dispatch_ctr.inc(replica=replica.rid)
            budget = deadline - now + _DISPATCH_SLACK_S
            if attempts > 1:
                # Failover attempts carry the REMAINING deadline, never
                # the client's original: the next replica must not be
                # promised budget that is already spent (it would serve
                # an abandoned request, and the router's own transport
                # timeout would then be miscounted as a replica fault).
                send_body = _rewrite_deadline(
                    body, (deadline - now) * 1e3
                )
            replica._enter()
            try:
                with self.tracer.start(
                    "fleet.dispatch", kind="dispatch", parent=root,
                    replica=replica.rid, attempt=attempts,
                ):
                    status, rbody, rheaders = replica.transport.request(
                        "POST", "/predict", send_body, fwd_headers,
                        budget,
                    )
            except (OSError, http.client.HTTPException) as e:
                # HTTPException covers a replica dying mid-response
                # (RemoteDisconnected is an OSError, BadStatusLine and
                # IncompleteRead are not) — all of them are the same
                # routing fact: this replica failed this request.
                replica.breaker.record_failure(
                    f"{type(e).__name__}: {e}"
                )
                tried.append(replica.rid)
                last = (
                    502,
                    json.dumps({
                        "error": f"replica {replica.rid} unreachable: "
                                 f"{type(e).__name__}",
                    }).encode(),
                    {},
                )
                self.retries_ctr.inc(reason="transport_error")
                continue
            finally:
                replica._exit()
            if status == 200:
                replica.breaker.record_success()
                self._done(root, t0, "ok", replica.rid, attempts, tier)
                return status, rbody, rheaders
            if status in (500, 502):
                replica.breaker.record_failure(f"HTTP {status}")
                tried.append(replica.rid)
                last = (status, rbody, rheaders)
                self.retries_ctr.inc(reason=f"http_{status}")
                continue
            if status == 503:
                # A replica-side shed is healthy overload behavior, not
                # a replica fault: no breaker hit, but another replica's
                # queue may have room — fail over.
                tried.append(replica.rid)
                last = (status, rbody, rheaders)
                self.sheds_ctr.inc(replica=replica.rid)
                self.retries_ctr.inc(reason="replica_shed")
                continue
            # 504 (deadline burned replica-side) and 4xx are final: the
            # backend is healthy, the request itself is done/denied.
            replica.breaker.record_success()
            self._done(root, t0, f"http_{status}", replica.rid,
                       attempts, tier)
            return status, rbody, rheaders

    def dispatch_generate(
        self,
        body: bytes,
        *,
        deadline: float,
        affinity: Optional[str] = None,
        headers: Optional[Dict[str, str]] = None,
        ctx: Optional[TraceContext] = None,
        tier: str = DEFAULT_TIER,
    ) -> Tuple[int, Any, Dict[str, str], Optional[str]]:
        """Route one LM ``/generate``: prefix-affinity pick, failover
        ONLY before the stream opens (503 shed / refused connect — no
        tokens were produced); a mid-stream failure is the caller's to
        surface, never retried. Returns ``(status, payload, headers,
        replica_id)`` — payload is a line iterator on 200."""
        t0 = self._clock()
        root = self.tracer.start(
            "fleet.request", kind="request", ctx=ctx, fresh=True,
            tier=tier, lm=True,
        )
        fwd_headers = self._forward_headers(headers, ctx, root)
        tried: List[str] = []
        attempts = 0
        last: Tuple[int, Any, Dict[str, str], Optional[str]] = (
            503,
            json.dumps({"error": "shed", "reason": "no_replica"}).encode(),
            {"Retry-After": f"{_NO_REPLICA_RETRY_AFTER_S:.3f}"},
            None,
        )
        send_body = body
        while True:
            now = self._clock()
            if now >= deadline:
                self._done(root, t0, "deadline", None, attempts, tier)
                return (
                    504,
                    json.dumps({
                        "error": "deadline exceeded at router",
                    }).encode(),
                    {}, None,
                )
            if attempts >= self.max_attempts:
                self._done(root, t0, f"gave_up_{last[0]}", None,
                           attempts, tier)
                return last
            replica = self.pick(exclude=tried, affinity=affinity)
            if replica is None or not replica.breaker.allow():
                if replica is not None:
                    tried.append(replica.rid)
                    continue
                self._done(root, t0, "no_replica", None, attempts, tier)
                return last
            attempts += 1
            self.dispatch_ctr.inc(replica=replica.rid)
            budget = deadline - now + _DISPATCH_SLACK_S
            if attempts > 1:
                send_body = _rewrite_deadline(
                    body, (deadline - now) * 1e3
                )
            replica._enter()
            try:
                status, payload, rheaders = replica.transport.stream(
                    "/generate", send_body, fwd_headers, budget
                )
            except (OSError, http.client.HTTPException) as e:
                replica._exit()
                # The connect/send failed — no stream, no tokens: the
                # one LM failover case that is provably idempotent.
                replica.breaker.record_failure(
                    f"{type(e).__name__}: {e}"
                )
                tried.append(replica.rid)
                last = (
                    502,
                    json.dumps({
                        "error": f"replica {replica.rid} unreachable: "
                                 f"{type(e).__name__}",
                    }).encode(),
                    {}, None,
                )
                self.retries_ctr.inc(reason="transport_error")
                continue
            if status == 503:
                replica._exit()
                tried.append(replica.rid)
                last = (status, payload, rheaders, replica.rid)
                self.sheds_ctr.inc(replica=replica.rid)
                self.retries_ctr.inc(reason="replica_shed")
                continue
            if status in (500, 502):
                replica._exit()
                replica.breaker.record_failure(f"HTTP {status}")
                tried.append(replica.rid)
                last = (status, payload, rheaders, replica.rid)
                self.retries_ctr.inc(reason=f"http_{status}")
                continue
            if status == 200:
                replica.breaker.record_success()
                # The stream outlives this call: keep the replica's
                # in-flight count (the least-loaded signal) held until
                # the caller closes/exhausts it.
                payload = _CountedStream(payload, replica._exit)
            else:
                replica._exit()
            self._done(
                root, t0, "ok" if status == 200 else f"http_{status}",
                replica.rid, attempts, tier,
            )
            return status, payload, rheaders, replica.rid

    def _done(
        self, root: Any, t0: float, status: str,
        replica: Optional[str], attempts: int, tier: str,
    ) -> None:
        self.requests_ctr.inc(status=status)
        root.end(status, replica=replica, attempts=attempts)
        ms = round((self._clock() - t0) * 1e3, 3)
        if self.slo is not None:
            # 4xx is a client error, not fleet unavailability — the
            # Google availability convention (5xx/timeouts burn budget).
            ok = status == "ok" or status.startswith("http_4")
            self.slo.observe_request(ok, latency_ms=ms)
        if self.telemetry is not None:
            self.telemetry.emit(
                "fleet_dispatch", status=status, replica=replica,
                attempts=attempts, tier=tier, ms=ms,
            )

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        reps = self.replicas()
        return {
            "replicas": [r.snapshot() for r in sorted(
                reps, key=lambda r: r.seq
            )],
            "live": sum(1 for r in reps if r.healthy),
            "registered": len(reps),
        }
