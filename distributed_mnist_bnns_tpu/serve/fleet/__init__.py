"""serve.fleet — multi-replica serving: router, supervisor, rollouts.

The fleet layer between "one hardened server" and real traffic
(ROADMAP item 1; Dean & Barroso's Tail at Scale argues tail tolerance
must live HERE, not in any single replica):

  router.py      deadline-aware least-loaded dispatch over N replicas,
                 per-replica health probing + circuit breakers,
                 retry-on-another-replica inside the client deadline,
                 x-jg-trace forwarded unchanged, prefix-affinity
                 routing for LM fleets (rendezvous hash of the first
                 page-size prompt block)
  supervisor.py  replica subprocesses booted --aot from the warm store,
                 reap + respawn with jittered backoff, autoscaling
                 between min/max off sustained queue depth + shed rate
  rollout.py     rolling deploys: artifact shipped over utils/transfer
                 (digest-verified), canary reload with health + error-
                 rate gates, automatic fleet-wide rollback on a trip
  remote.py      replicas on another machine: a JSON-line HostAgent on
                 the replica host (spawn/poll/signal + artifact staging
                 by digest over utils/transfer) and a RemoteLauncher
                 whose Popen-shaped handles plug into the same
                 supervisor — probes/breakers/respawn unchanged
  server.py      the `cli fleet` HTTP front end + SIGTERM drain
  harness.py     importable 3-replica availability-under-chaos probe
                 (the perf gate's fleet_availability_under_chaos band)

None of these modules import jax — the replicas do the inference; the
fleet process is pure control plane. See SERVING.md "Fleet",
OBSERVABILITY.md for the fleet_dispatch / replica_health / autoscale /
rollout event schema, and tests/test_fleet.py + scripts/fleet_smoke.py
for the acceptance scenarios.
"""

from .remote import HostAgent, RemoteLauncher, RemoteProcess
from .router import (
    HttpTransport,
    Replica,
    RouterCore,
    affinity_key,
)
from .rollout import RolloutManager, stage_artifact
from .server import FleetConfig, FleetServer
from .supervisor import (
    Autoscaler,
    FleetView,
    ReplicaSupervisor,
)

__all__ = [
    "Autoscaler",
    "FleetConfig",
    "FleetServer",
    "FleetView",
    "HostAgent",
    "HttpTransport",
    "RemoteLauncher",
    "RemoteProcess",
    "Replica",
    "ReplicaSupervisor",
    "RolloutManager",
    "RouterCore",
    "affinity_key",
    "stage_artifact",
]
