"""Rolling deploys: ship → canary → health-gated promote → rollback.

A new artifact reaches a running fleet in three stages, none of which
stops traffic (the replica's ``/admin/reload`` loads + warms the new
weights OFF the serving path and swaps atomically — serve/server.py —
so a mid-rollout fleet serves every request from either the old or the
new artifact, never neither):

  1. **ship** — :func:`stage_artifact` moves the artifact bytes into
     the fleet's staging directory over ``utils/transfer`` (the
     length-prefixed, sha256-verify-before-rename protocol): a
     truncated or bit-flipped ship is rejected at the wire, never
     handed to a replica. Each rollout stages into its own numbered
     subdirectory so the previous artifact stays on disk for rollback.
  2. **canary** — ONE replica reloads first and must pass the gate:
     the reload call itself succeeded, ``/healthz`` reports ``ok``
     again within the budget, and a burst of live probe requests
     through the replica keeps its error rate under the trip
     threshold. A bad artifact — unloadable, fence-tripping, or
     serving garbage — stops here, with one replica briefly degraded
     and instantly rolled back.
  3. **promote / rollback** — the remaining replicas reload one at a
     time behind the same gate. ANY trip rolls the WHOLE fleet back to
     the previous artifact (the replicas already promoted reload the
     old path — the same off-path swap, so rollback drops nothing
     either), and the supervisor keeps respawning from the old
     artifact. On full promotion the supervisor's spawn artifact
     advances, so autoscale-ups and respawns boot the new weights.

Every stage lands as a ``rollout`` event (``phase`` =
start/ship/canary_ok/promoted/trip/rolled_back/complete) — the state
machine is replayable from the event log alone. See SERVING.md
"Fleet".
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .router import Replica, RouterCore
from .supervisor import ReplicaSupervisor, free_port

log = logging.getLogger(__name__)

ROLLOUTS_TOTAL = "fleet_rollouts_total"


def stage_artifact(
    src: str,
    staging_dir: str,
    *,
    host: str = "127.0.0.1",
    timeout: float = 60.0,
) -> str:
    """Ship ``src`` into ``staging_dir`` over the digest-verified
    ``utils/transfer`` protocol (loopback here; the same call with a
    remote receiver ships across machines). Returns the staged path.
    The sha256 is verified before the atomic rename AND echoed in the
    ack — corruption fails the ship on both sides."""
    from ...utils.transfer import receive_file, send_file

    port = free_port(host)
    result: Dict[str, Any] = {}

    def recv() -> None:
        try:
            result["path"], result["bytes"] = receive_file(
                staging_dir, port, host=host, timeout=timeout
            )
        except BaseException as e:  # surfaced to the sender side below
            result["error"] = e

    thread = threading.Thread(target=recv, name="rollout-recv",
                              daemon=True)
    thread.start()
    send_file(src, host, port, timeout=timeout)
    thread.join(timeout=timeout)
    if "path" not in result:
        err = result.get("error")
        raise IOError(
            f"artifact ship into {staging_dir} did not complete"
            + (f": {type(err).__name__}: {err}" if err else "")
        )
    return str(result["path"])


class RolloutTrip(RuntimeError):
    """Internal signal: a gate failed; carries the reason."""


class RolloutManager:
    """Drives the rolling-reload state machine over a router's live
    replicas. ``probe_body`` is a valid ``/predict`` JSON body (the
    fleet server builds one from its configured input shape); tests
    may replace :attr:`probe_fn` wholesale."""

    def __init__(
        self,
        router: RouterCore,
        *,
        artifact: str,
        supervisor: Optional[ReplicaSupervisor] = None,
        telemetry: Any = None,
        staging_dir: Optional[str] = None,
        probe_body: Optional[bytes] = None,
        probe_n: int = 8,
        error_rate_limit: float = 0.34,
        reload_timeout_s: float = 120.0,
        health_timeout_s: float = 15.0,
    ):
        self.router = router
        self.supervisor = supervisor
        self.telemetry = telemetry
        self.current_artifact = artifact
        self.staging_dir = staging_dir
        self.probe_body = probe_body
        self.probe_n = int(probe_n)
        self.error_rate_limit = float(error_rate_limit)
        self.reload_timeout_s = float(reload_timeout_s)
        self.health_timeout_s = float(health_timeout_s)
        self.probe_fn: Callable[[Replica], Tuple[int, str]] = (
            self._default_probe
        )
        self._lock = threading.Lock()   # one rollout at a time
        self._roll_seq = 0
        reg = telemetry.registry if telemetry is not None else None
        if reg is None:
            from ...obs import default_registry

            reg = default_registry()
        self.rollouts_ctr = reg.counter(
            ROLLOUTS_TOTAL, "rolling deploys by outcome"
        )

    def _emit(self, phase: str, **fields: Any) -> None:
        if self.telemetry is not None:
            self.telemetry.emit("rollout", phase=phase, **fields)

    def _decision(self, action: str, **fields: Any) -> None:
        """Control-plane decision audit record (OBSERVABILITY.md): gate
        verdicts and rollbacks carry the thresholds and observations
        that produced them, for ``cli fleet explain``."""
        if self.telemetry is not None:
            self.telemetry.emit("decision", actor="rollout",
                                action=action, **fields)

    # -- gates ---------------------------------------------------------------

    def _default_probe(self, replica: Replica) -> Tuple[int, str]:
        """One live probe request straight at the replica; returns
        ``(errors, detail)`` for a single attempt (0 or 1 errors).
        Replica sheds (503) are overload, not artifact badness — they
        count as neutral and are retried by the caller's loop."""
        if self.probe_body is None:
            return 0, "no probe body configured"
        try:
            status, body, _ = replica.transport.request(
                "POST", "/predict", self.probe_body, {}, 10.0
            )
        except (OSError, ConnectionError,
                http.client.HTTPException) as e:
            return 1, f"transport: {type(e).__name__}"
        if status == 200:
            return 0, "ok"
        if status == 503:
            return 0, "shed"
        return 1, f"http_{status}"

    def _reload_one(self, replica: Replica, artifact: str) -> Tuple[
        bool, str
    ]:
        body = json.dumps({"artifact": artifact}).encode()
        try:
            status, rbody, _ = replica.transport.request(
                "POST", "/admin/reload", body, {},
                self.reload_timeout_s,
            )
        except (OSError, ConnectionError,
                http.client.HTTPException) as e:
            return False, f"reload transport: {type(e).__name__}: {e}"
        if status != 200:
            return False, (
                f"reload http_{status}: "
                f"{rbody[:200].decode('utf-8', 'replace')}"
            )
        return True, "reloaded"

    def _gate(self, replica: Replica, artifact: str) -> Dict[str, Any]:
        """Reload ``replica`` to ``artifact`` and hold it to the
        promotion gate; raises :class:`RolloutTrip` on any failure.
        Returns the gate observations (probe counts / error rate) for
        the decision audit record."""
        ok, detail = self._reload_one(replica, artifact)
        if not ok:
            raise RolloutTrip(detail)
        # health gate: /healthz must come back ok (a fence trip or a
        # failed engine after the swap shows up here)
        deadline = time.monotonic() + self.health_timeout_s
        healthy = False
        while time.monotonic() < deadline:
            try:
                status, body, _ = replica.transport.request(
                    "GET", "/healthz", None, {}, 5.0
                )
                health = json.loads(body)
            except (OSError, ValueError,
                    http.client.HTTPException):
                time.sleep(0.05)
                continue
            if status == 200 and health.get("status") == "ok" \
                    and not health.get("fence_error"):
                healthy = True
                break
            time.sleep(0.05)
        if not healthy:
            raise RolloutTrip("post-reload health gate timed out")
        # error-rate gate: live probes through the new weights. Sheds
        # (503) are overload, not artifact badness — they are RETRIED,
        # not counted as success: the gate must observe probe_n real
        # outcomes, or refuse to promote at all (a canary that sheds
        # every probe under saturation has proven nothing about the
        # new artifact).
        errors = 0
        samples = 0
        details: List[str] = []
        for _ in range(self.probe_n * 5):
            if samples >= self.probe_n:
                break
            e, detail = self.probe_fn(replica)
            if detail == "shed":
                time.sleep(0.05)
                continue
            samples += 1
            errors += e
            if e:
                details.append(detail)
        if samples == 0:
            raise RolloutTrip(
                "canary gate got no probe through (every attempt "
                "shed) — cannot validate the new artifact"
            )
        rate = errors / samples
        if rate > self.error_rate_limit:
            raise RolloutTrip(
                f"canary error rate {rate:.2f} > "
                f"{self.error_rate_limit:.2f} over {samples} probe(s) "
                f"({details[:3]})"
            )
        return {
            "probes": samples,
            "probe_errors": errors,
            "error_rate": round(rate, 4),
            "error_rate_limit": self.error_rate_limit,
        }

    # -- the state machine ---------------------------------------------------

    def rolling_reload(
        self, artifact: str, *, ship: Optional[bool] = None
    ) -> Dict[str, Any]:
        """Roll ``artifact`` across every healthy replica, one at a
        time, canary first. Returns the outcome dict; ``status`` is
        ``promoted`` or ``rolled_back`` (with the tripped replica and
        reason). ``ship`` stages the artifact through utils/transfer
        first (default: when a staging dir is configured)."""
        with self._lock:
            return self._rolling_reload_locked(artifact, ship)

    def _rolling_reload_locked(
        self, artifact: str, ship: Optional[bool]
    ) -> Dict[str, Any]:
        prev = self.current_artifact
        if ship is None:
            ship = self.staging_dir is not None
        if ship:
            if self.staging_dir is None:
                raise ValueError("ship=True needs a staging_dir")
            self._roll_seq += 1
            dest = os.path.join(
                self.staging_dir, f"roll-{self._roll_seq:04d}"
            )
            staged = stage_artifact(artifact, dest)
            self._emit("ship", src=artifact, staged=staged)
            artifact = staged
        replicas = sorted(
            (r for r in self.router.replicas() if r.healthy),
            key=lambda r: r.seq,
        )
        if not replicas:
            raise RuntimeError("no healthy replica to roll out to")
        self._emit(
            "start", artifact=artifact, previous=prev,
            replicas=[r.rid for r in replicas],
        )
        promoted: List[Replica] = []
        for i, replica in enumerate(replicas):
            try:
                gate = self._gate(replica, artifact)
            except RolloutTrip as trip:
                self._emit(
                    "trip", replica=replica.rid, reason=str(trip),
                    canary=(i == 0),
                )
                self._decision(
                    "gate_trip", replica=replica.rid,
                    inputs={
                        "reason": str(trip),
                        "canary": i == 0,
                        "artifact": artifact,
                        "error_rate_limit": self.error_rate_limit,
                        "probe_n": self.probe_n,
                    },
                )
                log.error(
                    "rollout of %s tripped at %s (%s) — rolling the "
                    "fleet back to %s", artifact, replica.rid, trip,
                    prev,
                )
                rolled: List[str] = []
                for rb in (*promoted, replica):
                    ok, detail = self._reload_one(rb, prev)
                    if ok:
                        rolled.append(rb.rid)
                    else:
                        # best-effort: an unreachable replica respawns
                        # from the supervisor's (old) artifact anyway
                        log.error(
                            "rollback reload of %s failed: %s",
                            rb.rid, detail,
                        )
                self.rollouts_ctr.inc(outcome="rolled_back")
                self._emit(
                    "rolled_back", artifact=prev,
                    tripped=replica.rid, reason=str(trip),
                    rolled=rolled,
                )
                self._decision(
                    "rollback",
                    inputs={
                        "tripped": replica.rid,
                        "reason": str(trip),
                        "rolled": rolled,
                        "artifact": prev,
                    },
                )
                return {
                    "status": "rolled_back",
                    "tripped": replica.rid,
                    "reason": str(trip),
                    "rolled": rolled,
                    "artifact": prev,
                }
            promoted.append(replica)
            self._emit(
                "canary_ok" if i == 0 else "promoted",
                replica=replica.rid, artifact=artifact,
            )
            self._decision(
                "gate_pass", replica=replica.rid,
                inputs={"canary": i == 0, "artifact": artifact, **gate},
            )
        self.current_artifact = artifact
        if self.supervisor is not None:
            # future respawns / scale-ups boot the promoted artifact
            self.supervisor.artifact = artifact
        self.rollouts_ctr.inc(outcome="promoted")
        self._emit(
            "complete", artifact=artifact,
            replicas=[r.rid for r in promoted],
        )
        log.info(
            "rollout complete: %d replica(s) on %s",
            len(promoted), artifact,
        )
        return {
            "status": "promoted",
            "artifact": artifact,
            "replicas": [r.rid for r in promoted],
        }
