"""Importable fleet availability-under-chaos probe (ROADMAP item 5).

The fleet twin of serve/harness.py's p99 probe: drive a REAL 3-replica
fleet — three live :class:`~..core.ServeEngine` micro-batchers behind
the REAL :class:`~.router.RouterCore` dispatch policy (in-process
transports, no sockets: the perf gate needs determinism and sub-10s
wall clock, and the policy code is identical either way) — at
saturation, then KILL one replica mid-probe. The router must absorb it:
transport errors trip that replica's breaker, the prober ejects it,
and every request that failed there retries on a surviving replica
inside its deadline.

The headline number is **availability**: the fraction of client
requests that completed 200 within their deadline, measured across the
whole window INCLUDING the kill. ``scripts/perf_gate.py`` bands it as
``fleet_availability_under_chaos`` (floor 0.99 — the ISSUE 15
acceptance), and a band trip prints the per-replica health/breaker
transition log this section carries, so the failure explains itself
(which replica flapped, when, why).

Chaos composes the same way as the single-server smoke: the doomed
replica also takes scripted ``infer_slow`` stalls before dying, so the
failover path is exercised against a straggler, not only a corpse.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ...resilience.policy import CircuitBreaker
from ..core import AdmissionQueue, ServeEngine
from .router import RouterCore

# The engine-side wait slack (mirrors serve/server.py._WAIT_SLACK_S).
_WAIT_SLACK_S = 0.05


class EngineReplicaTransport:
    """The router transport interface over an in-process ServeEngine:
    ``/predict`` and ``/healthz`` with the same status semantics as
    serve/server.py, minus the sockets. ``kill()`` makes every call
    raise — the wire behavior of a dead process."""

    def __init__(self, rid: str, engine: ServeEngine,
                 input_shape=(28, 28, 1)):
        self.rid = rid
        self.engine = engine
        self.input_shape = input_shape
        self.dead = False

    def kill(self) -> None:
        self.dead = True
        self.engine.stop()

    def request(self, method: str, path: str, body: Optional[bytes],
                headers: Dict[str, str], timeout: float):
        if self.dead:
            raise ConnectionError(f"{self.rid} killed")
        if method == "GET" and path == "/healthz":
            status = (
                "failed" if self.engine.fence_error is not None
                else "draining" if self.engine.draining else "ok"
            )
            return 200, json.dumps({
                "status": status,
                "queue_depth": len(self.engine.queue),
                "fence_error": self.engine.fence_error,
            }).encode(), {}
        if method == "POST" and path == "/predict":
            return self._predict(body or b"{}")
        return 404, b'{"error": "no route"}', {}

    def _predict(self, raw: bytes):
        payload = json.loads(raw)
        images = np.asarray(payload["images"], np.float32)
        deadline = time.monotonic() + float(
            payload.get("deadline_ms", 1000.0)
        ) / 1e3
        req = self.engine.submit(images, deadline)
        if isinstance(req, str):
            return 503, json.dumps(
                {"error": "shed", "reason": req}
            ).encode(), {"Retry-After": "0.050"}
        # Chunked wait so a kill() mid-request surfaces as the reset
        # connection a dead process would give the router (which then
        # fails over), not a full client-deadline burn.
        end = deadline + _WAIT_SLACK_S
        while not req.event.wait(0.02):
            if self.dead:
                raise ConnectionError(f"{self.rid} connection reset")
            if time.monotonic() >= end:
                req.finish("deadline", error="deadline exceeded")
                break
        if req.status == "ok":
            lp = req.log_probs
            return 200, json.dumps({
                "argmax": [int(i) for i in lp.argmax(-1)],
            }).encode(), {}
        if req.status == "deadline":
            return 504, b'{"error": "deadline exceeded"}', {}
        if req.status in ("shed", "breaker_open"):
            return 503, json.dumps({
                "error": "shed", "reason": req.status,
            }).encode(), {"Retry-After": "0.050"}
        return 502, json.dumps({
            "error": req.error or "backend failure",
        }).encode(), {}

    def stream(self, path, body, headers, timeout):
        raise NotImplementedError("classifier fleet probe only")


class _CaptureTelemetry:
    """Just enough of the Telemetry facade for the chaos probe: a live
    registry (the router's counters need one) plus an in-memory event
    list — so the SLO monitor's ``slo_alert``s and the control plane's
    ``decision``s land in the returned section instead of a log dir."""

    def __init__(self):
        from ...obs import MetricsRegistry

        self.registry = MetricsRegistry()
        self.events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()

    def emit(self, kind: str, **fields: Any) -> None:
        with self._lock:
            self.events.append({"kind": kind, **fields})

    def of_kind(self, kind: str) -> List[Dict[str, Any]]:
        with self._lock:
            return [e for e in self.events if e["kind"] == kind]


def _make_engine(
    predict_fn, *, batch_size: int, chaos: Any = None,
) -> ServeEngine:
    return ServeEngine(
        predict_fn,
        batch_size=batch_size,
        queue=AdmissionQueue(16),
        breaker=CircuitBreaker(
            failure_threshold=1 << 30, reset_timeout_s=3600.0,
        ),
        chaos=chaos,
        stall_timeout_s=3600.0,
        linger_s=0.001,
    ).start()


def fleet_availability_section(
    *,
    replicas: int = 3,
    batch_size: int = 8,
    n_threads: int = 8,
    duration_s: float = 3.0,
    deadline_ms: float = 1500.0,
    kill_after_s: float = 1.0,
    interpret: bool = True,
    seed: int = 0,
    telemetry: Any = None,
) -> Dict[str, Any]:
    """The bench-record section (``fleet_availability``): saturate a
    3-replica fleet through the real router, chaos-stall then KILL one
    replica mid-window, report the end-to-end success fraction plus the
    per-replica transition log a tripped band prints."""
    from ...obs import SLOMonitor, default_fleet_slos
    from ...resilience.chaos import ChaosController, reset_fire_counts
    from ..harness import make_tiny_packed_predictor

    predict_fn, input_shape = make_tiny_packed_predictor(
        batch_size, interpret=interpret, seed=seed
    )
    reset_fire_counts()
    capture = telemetry if telemetry is not None else _CaptureTelemetry()
    slo = SLOMonitor(
        default_fleet_slos(
            request_p99_ms=deadline_ms,
            fast_window_s=max(duration_s / 6.0, 0.1),
            slow_window_s=max(duration_s / 2.0, 0.3),
        ),
        registry=getattr(capture, "registry", None),
        emit=capture.emit,
    )
    router = RouterCore(
        telemetry=capture,
        breaker_threshold=2,
        breaker_reset_s=0.5,
        max_attempts=replicas,
        slo=slo,
    )
    transports: List[EngineReplicaTransport] = []
    for i in range(replicas):
        # The doomed replica (0) staggers first: scripted stalls make
        # it a straggler before the kill makes it a corpse.
        chaos = None
        if i == 0:
            chaos = ChaosController.from_config(
                "infer_slow@step=3,times=2,delay_s=0.2",
                seed=seed, telemetry=capture,
            )
        engine = _make_engine(
            predict_fn, batch_size=batch_size, chaos=chaos,
        )
        transport = EngineReplicaTransport(
            f"fleet-r{i}", engine, input_shape
        )
        transports.append(transport)
        router.add_replica(transport.rid, transport)
    router.start_prober(0.05)

    ok = 0
    total = 0
    outcomes: Dict[str, int] = {}
    lock = threading.Lock()
    stop_at = time.monotonic() + duration_s

    def hammer(tid: int) -> None:
        nonlocal ok, total
        rng = np.random.RandomState(tid)
        body = json.dumps({
            "images": rng.randn(1, *input_shape).astype(
                np.float32
            ).tolist(),
            "deadline_ms": deadline_ms,
        }).encode()
        while time.monotonic() < stop_at:
            status, _, _ = router.dispatch_predict(
                body, deadline=time.monotonic() + deadline_ms / 1e3,
            )
            with lock:
                total += 1
                outcomes[str(status)] = outcomes.get(str(status), 0) + 1
                if status == 200:
                    ok += 1

    threads = [
        threading.Thread(target=hammer, args=(i,), daemon=True)
        for i in range(n_threads)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(min(kill_after_s, duration_s))
    transports[0].kill()
    killed_at = time.monotonic() - t0
    for t in threads:
        t.join(timeout=duration_s + deadline_ms / 1e3 + 30.0)
    wall = time.monotonic() - t0
    router.stop_prober()
    slo.evaluate()      # final pass so a still-open burn is visible
    for transport in transports[1:]:
        transport.engine.begin_drain()
        transport.engine.drain(timeout=5.0)
        transport.engine.stop()
    reset_fire_counts()
    events_of = getattr(capture, "of_kind", lambda kind: [])
    return {
        "replicas": replicas,
        "n_threads": n_threads,
        "duration_s": round(wall, 3),
        "killed_replica": transports[0].rid,
        "killed_at_s": round(killed_at, 3),
        "requests_total": total,
        "requests_ok": ok,
        "availability": round(ok / total, 5) if total else None,
        "outcomes": outcomes,
        "retries_total": int(router.retries_ctr.total()),
        "replica_transitions": {
            r.rid: r.transitions for r in sorted(
                router.replicas() or [], key=lambda r: r.seq
            )
        },
        "slo": slo.summary(),
        "slo_alerts": events_of("slo_alert"),
        "decisions": events_of("decision"),
        "interpret_mode": interpret,
    }
