"""Serving data plane: requests, admission queue, micro-batching engine.

The Tail-at-Scale mechanics live here, independent of the transport:

  * every request carries a **deadline**; work whose deadline has
    passed is cancelled when the batcher pops it (never computed), and
    a waiter that gives up claims the request so the engine drops it —
    both sides race through ``Request.finish``, exactly one wins;
  * admission is a **bounded queue**: when it is full the request is
    rejected immediately (``shed`` event + counter) instead of growing
    an unbounded backlog that turns a brownout into a collapse;
  * admission is **SLO-tier aware**: every request carries a ``tier``
    (``interactive`` outranks ``batch``); the bounded queue pops the
    highest tier first, and a full queue admits an interactive request
    by displacing the newest queued batch request — under saturation
    sheds hit the low tier first (``serve_shed_total`` and the ``shed``
    event carry a ``tier`` label). The fleet router (serve/fleet)
    reuses this machinery verbatim;
  * a **micro-batcher** coalesces queued requests up to the compiled
    batch shape (padding the remainder), so the jitted predictor only
    ever sees one batch shape — no recompiles under bursty load;
  * the predictor call sits behind a :class:`~..resilience.policy.
    CircuitBreaker`: consecutive exceptions OR stalls past the stall
    budget trip it open, and while open the admission path fast-fails
    (``breaker.admits()``) without consuming the half-open probe the
    worker's ``allow()`` must issue.

Chaos (``infer_slow`` / ``infer_error``) hooks the same predictor call,
so CI continuously proves shed/breaker/drain behavior rather than only
the happy path (RESILIENCE.md, crash-only design: the recovery path IS
the exercised path).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..analysis.guards import RecompileFenceError
from ..obs.costs import get_ledger
from ..obs.profile import STEP_MARKER, get_profiler
from ..obs.trace import (
    NULL_SPAN,
    NULL_TRACER,
    TraceContext,
    next_request_id,
)

log = logging.getLogger(__name__)

REQUESTS_TOTAL = "serve_requests_total"
SHED_TOTAL = "serve_shed_total"
BATCHES_TOTAL = "serve_batches_total"
BATCH_SECONDS = "serve_batch_seconds"
QUEUE_DEPTH = "serve_queue_depth"
BREAKER_TRANSITIONS_TOTAL = "serve_breaker_transitions_total"

# SLO tiers, highest priority first. Admission pops high tiers first
# and, at a full queue, displaces the newest lowest-tier request to
# admit a higher-tier one — reject-the-cheap over reject-the-urgent.
TIERS = ("interactive", "batch")
DEFAULT_TIER = TIERS[0]
_TIER_RANK = {t: i for i, t in enumerate(TIERS)}


class Request:
    """One admitted prediction request.

    The handler thread waits on ``event``; the engine fills the result.
    ``finish`` is claim-once: the first caller (engine delivering, or a
    deadline-expired waiter abandoning) wins, the loser's call returns
    False and must not touch the payload.
    """

    __slots__ = (
        "id", "images", "n", "deadline", "enqueued_at", "event",
        "status", "log_probs", "error", "span", "tier", "_lock", "_done",
    )

    def __init__(self, images: np.ndarray, deadline: float,
                 tier: str = DEFAULT_TIER):
        # Run-scoped id (obs/trace): nonce-prefixed so ids never collide
        # across replicas nor repeat across restarts — the join key
        # between `request` events and span trees must be globally
        # unique for a fleet-wide log merge.
        self.id = next_request_id()
        self.images = images
        self.n = int(images.shape[0])
        self.deadline = deadline
        self.tier = tier
        self.enqueued_at = time.monotonic()
        self.event = threading.Event()
        self.status: Optional[str] = None
        self.log_probs: Optional[np.ndarray] = None
        self.error = ""
        self.span = NULL_SPAN      # root trace span, set at admission
        self._lock = threading.Lock()
        self._done = False

    def finish(
        self, status: str, *,
        log_probs: Optional[np.ndarray] = None, error: str = "",
    ) -> bool:
        """Resolve the request; returns False if already resolved."""
        with self._lock:
            if self._done:
                return False
            self._done = True
            self.status = status
            self.log_probs = log_probs
            self.error = error
        self.event.set()
        return True

    def expired(self, now: Optional[float] = None) -> bool:
        return (time.monotonic() if now is None else now) >= self.deadline


class AdmissionQueue:
    """Bounded, SLO-tier-aware queue with a blocking batch pop.

    ``try_put`` never blocks — a full queue is the caller's signal to
    shed. ``put_or_displace`` additionally lets a higher-tier request
    displace the newest queued lower-tier one when the queue is full
    (the displaced request is returned so the caller can resolve it as
    shed). ``pop_batch`` blocks for the first request (bounded by
    ``timeout``), then lingers briefly to coalesce more, popping
    requests in tier-priority order (FIFO within a tier) while their
    examples fit ``max_examples``.
    """

    def __init__(self, maxsize: int):
        self.maxsize = int(maxsize)
        self._items: deque[Request] = deque()
        self._cond = threading.Condition()

    def __len__(self) -> int:
        with self._cond:
            return len(self._items)

    def try_put(self, req: Request) -> bool:
        with self._cond:
            if len(self._items) >= self.maxsize:
                return False
            self._items.append(req)
            self._cond.notify()
            return True

    def put_or_displace(
        self, req: Request
    ) -> "tuple[bool, Optional[Request]]":
        """``(admitted, displaced)``. A full queue admits ``req`` by
        evicting the NEWEST queued request of a strictly lower tier
        (newest: it has waited least, so evicting it wastes the least
        queue time); the victim is returned for the caller to resolve
        as shed. No lower-tier victim -> ``(False, None)`` and the
        caller sheds ``req`` itself."""
        with self._cond:
            if len(self._items) < self.maxsize:
                self._items.append(req)
                self._cond.notify()
                return True, None
            rank = _TIER_RANK.get(req.tier, 0)
            for i in range(len(self._items) - 1, -1, -1):
                victim = self._items[i]
                if _TIER_RANK.get(victim.tier, 0) > rank:
                    del self._items[i]
                    self._items.append(req)
                    self._cond.notify()
                    return True, victim
            return False, None

    def _best_index_locked(self) -> int:  # holds-lock: _cond
        """Index of the pop head: oldest request of the highest queued
        tier (lock held, queue non-empty)."""
        best, best_rank = 0, None
        for i, r in enumerate(self._items):
            rank = _TIER_RANK.get(r.tier, 0)
            if best_rank is None or rank < best_rank:
                best, best_rank = i, rank
                if rank == 0:
                    break
        return best

    def wake(self) -> None:
        """Unblock a pending ``pop_batch`` (drain/stop path)."""
        with self._cond:
            self._cond.notify_all()

    def pop_batch(
        self, max_examples: int, *,
        linger_s: float = 0.0, timeout: float = 0.1,
        claim: Optional[Callable[[], None]] = None,
    ) -> List[Request]:
        """Up to ``max_examples`` worth of requests; ``[]`` on timeout.

        A request whose batch alone exceeds ``max_examples`` never
        fits — admission rejects those up front (server layer), so the
        head of the queue always makes progress here.

        ``claim`` runs under the queue lock before a non-empty batch is
        returned: the engine marks itself busy there, so a drain
        watcher can never observe "queue empty AND worker idle" while a
        popped batch is still unprocessed.
        """
        with self._cond:
            if not self._items:
                self._cond.wait(timeout)
                if not self._items:
                    return []
            if linger_s > 0:
                deadline = time.monotonic() + linger_s
                while True:
                    have = sum(r.n for r in self._items)
                    remaining = deadline - time.monotonic()
                    if have >= max_examples or remaining <= 0:
                        break
                    self._cond.wait(remaining)
            out: List[Request] = []
            total = 0
            while self._items:
                i = self._best_index_locked()
                req = self._items[i]
                if total + req.n > max_examples:
                    break
                del self._items[i]
                out.append(req)
                total += req.n
            if out and claim is not None:
                # jg: disable=JG010 -- holding the lock IS the point (PR 4 drain-race fix): claim flips the engine's busy flag under the queue lock so "queue empty AND worker idle" is never observable with a popped batch pending; it sets one bool and never re-enters the queue
                claim()
            return out


class ServeEngine:
    """Single-worker micro-batching inference engine.

    ``predict_fn`` is the jitted predictor from ``infer.load_packed``;
    it is only ever called from the worker thread, always at the
    compiled ``batch_size`` (padded), so one compile serves the whole
    run — and ``swap_predictor`` (hot reload) is a plain attribute
    write observed at the next batch.
    """

    def __init__(
        self,
        predict_fn: Callable,
        *,
        batch_size: int,
        queue: AdmissionQueue,
        breaker: Any,
        chaos: Any = None,
        telemetry: Any = None,
        stall_timeout_s: float = 1.0,
        linger_s: float = 0.002,
        sanitizer: Any = None,
    ):
        self.predict_fn = predict_fn
        self.batch_size = int(batch_size)
        self.queue = queue
        self.breaker = breaker
        self.chaos = chaos
        self.telemetry = telemetry
        self.stall_timeout_s = float(stall_timeout_s)
        self.linger_s = float(linger_s)
        # Recompile fence (analysis/guards.Sanitizer), armed by the
        # server when the boot came fully from the AOT store: the ONE
        # compiled batch shape means there is nothing left to compile,
        # so any post-boot XLA compile is a bug (a shape leak minting a
        # second jit signature) and must fail loudly rather than ship
        # as silent per-batch compile stalls. None = unfenced (today's
        # behavior for cold boots).
        self.sanitizer = sanitizer
        # Spans ride the telemetry sink's tracer (obs/trace); without
        # telemetry the shared NULL_TRACER keeps every instrumentation
        # site a single attribute check.
        self.tracer = getattr(telemetry, "tracer", None) or NULL_TRACER
        # Device introspection (obs/costs, obs/profile): both disabled-
        # by-default, both one attribute check on the hot path — the
        # ledger accumulates per-program dispatch times for measured
        # MFU; the profiler flag arms the StepTraceAnnotation markers
        # that join a device capture to this run's trace ids.
        self._ledger = get_ledger()
        self._profiler = get_profiler()
        self.fence_error: Optional[str] = None
        self.batch_seq = 0
        self.draining = False
        self._stop = threading.Event()
        self._busy = False
        self._thread: Optional[threading.Thread] = None
        reg = telemetry.registry if telemetry is not None else None
        if reg is None:
            from ..obs import default_registry

            reg = default_registry()
        self.requests_ctr = reg.counter(
            REQUESTS_TOTAL, "serving requests by final status"
        )
        self.shed_ctr = reg.counter(
            SHED_TOTAL, "admission rejections by reason"
        )
        self.batches_ctr = reg.counter(
            BATCHES_TOTAL, "predictor micro-batches dispatched"
        )
        self.batch_hist = reg.histogram(
            BATCH_SECONDS, "predictor call latency per micro-batch"
        )
        self.depth_gauge = reg.gauge(
            QUEUE_DEPTH, "admission queue depth at batch pop"
        )

    # -- admission (handler threads) ----------------------------------------

    def submit(
        self, images: np.ndarray, deadline: float,
        ctx: Optional[TraceContext] = None, tier: str = DEFAULT_TIER,
    ):
        """Admit or shed. Returns a :class:`Request`, or a shed-reason
        string (``draining`` | ``breaker_open`` | ``queue_full``).
        ``ctx`` is an adopted ``x-jg-trace`` context (obs/trace): the
        request's root span joins the client's trace; None mints a
        fresh trace per request. ``tier`` is the SLO class: at a full
        queue an ``interactive`` request may displace the newest queued
        ``batch`` one (the victim resolves as a shed, low tier first)."""
        if self.draining or self._stop.is_set():
            return self._shed("draining", ctx=ctx, tier=tier)
        if self.fence_error is not None:
            # The fence killed the worker: queueing would strand the
            # request until its deadline. Shed immediately and visibly
            # (health() reports failed) — same contract as the LM
            # engine's engine_failed.
            return self._shed("engine_failed", ctx=ctx, tier=tier)
        if not self.breaker.admits():
            return self._shed("breaker_open", ctx=ctx, tier=tier)
        req = Request(images, deadline, tier=tier)
        req.span = self.tracer.start(
            "serve.request", kind="request", ctx=ctx, fresh=True,
            id=req.id, n=req.n, tier=tier,
        )
        admitted, victim = self.queue.put_or_displace(req)
        if victim is not None:
            self._displace(victim)
        if not admitted:
            req.span.end("shed", reason="queue_full")
            return self._shed("queue_full", spanned=True, tier=tier)
        return req

    def _displace(self, victim: Request) -> None:
        """Resolve a queue-displaced lower-tier request as an explicit
        shed (reason ``displaced``): its waiter gets a prompt 503
        instead of queue time it was never going to get back."""
        if victim.finish(
            "shed", error="displaced by a higher-tier admission"
        ):
            self.shed_ctr.inc(reason="displaced", tier=victim.tier)
            self.requests_ctr.inc(status="shed")
            victim.span.end("shed", reason="displaced")
            if self.telemetry is not None:
                self.telemetry.emit(
                    "shed", reason="displaced", tier=victim.tier,
                    id=victim.id, queue_depth=len(self.queue),
                )

    def _shed(
        self, reason: str, *, ctx: Optional[TraceContext] = None,
        spanned: bool = False, tier: str = DEFAULT_TIER,
    ) -> str:
        self.shed_ctr.inc(reason=reason, tier=tier)
        self.requests_ctr.inc(status="shed")
        if not spanned and self.tracer.enabled:
            # Sheds are spans too (zero-length): the slow tail's
            # sibling outcomes stay joinable to the client's trace.
            now = time.monotonic()
            self.tracer.record(
                "serve.request", kind="request", t0=now, t1=now,
                ctx=ctx, fresh=True, status="shed", reason=reason,
                tier=tier,
            )
        if self.telemetry is not None:
            self.telemetry.emit(
                "shed", reason=reason, tier=tier,
                queue_depth=len(self.queue),
            )
        return reason

    # -- worker -------------------------------------------------------------

    def start(self) -> "ServeEngine":
        self._thread = threading.Thread(
            target=self._run, name="serve-engine", daemon=True
        )
        self._thread.start()
        return self

    def _claim_busy(self) -> None:
        self._busy = True

    def _run(self) -> None:
        while True:
            reqs = self.queue.pop_batch(
                self.batch_size, linger_s=self.linger_s, timeout=0.1,
                claim=self._claim_busy,
            )
            if not reqs:
                if self._stop.is_set() and not len(self.queue):
                    return
                continue
            try:
                self._process(reqs)
            except RecompileFenceError as e:
                # Budget-0 fence (AOT boot-from-store): a post-boot
                # compile broke the zero-compile contract. The batch's
                # requests were already resolved (the fence check runs
                # after delivery); fail the ENGINE loudly — /healthz
                # reports failed, submit() sheds engine_failed.
                self.fence_error = str(e)
                log.error("serve-engine recompile fence tripped: %s", e)
                return
            except Exception:
                # The worker must outlive ANY per-batch failure (e.g. a
                # full disk erroring the telemetry write): a dead worker
                # is a silent total outage behind a green healthz. The
                # batch's unresolved requests 504 at their deadlines.
                log.exception(
                    "serve-engine batch %d processing failed; worker "
                    "continues", self.batch_seq,
                )
            finally:
                self._busy = False

    def _process(self, reqs: List[Request]) -> None:
        self.batch_seq += 1
        self.depth_gauge.set(len(self.queue))
        now = time.monotonic()
        # queue wait ends at the pop — measured here so the reported
        # queue_ms/infer_ms split cleanly separates queueing pressure
        # from backend slowness.
        waits = {r.id: now - r.enqueued_at for r in reqs}
        live = []
        for r in reqs:
            if r.expired(now):
                self._finish(r, "deadline",
                             error="deadline exceeded in queue",
                             queue_s=waits[r.id])
            else:
                live.append(r)
        if not live:
            return
        if not self.breaker.allow():
            # open breaker: fast-fail everything the admission race let in
            for r in live:
                self._finish(
                    r, "breaker_open", error="circuit breaker open",
                    queue_s=waits[r.id],
                )
            return
        t0 = time.perf_counter()
        # Trace marks (monotonic, the span timebase): pop -> assembled
        # -> stall (chaos) -> dispatch done. Children are banked
        # retrospectively AFTER delivery, so tracing adds no I/O to the
        # dispatch itself.
        m_pop = now
        m_asm = now
        stall_s = 0.0
        try:
            # Assembly stays inside the try: admission validates shapes
            # against the served input shape, but a defect there must
            # fail THIS batch, never kill the worker thread (a dead
            # worker is a silent total outage behind a green healthz).
            x = np.concatenate([r.images for r in live], axis=0)
            pad = self.batch_size - x.shape[0]
            if pad:
                x = np.concatenate(
                    [x, np.zeros((pad, *x.shape[1:]), x.dtype)], axis=0
                )
            m_asm = time.monotonic()
            # The batch span is the worker thread's *current* span
            # while chaos + the predictor run, so a chaos fault fired
            # here parents its own span under this batch — fault ->
            # latency causality is a tree link, not a log-grep.
            with self.tracer.start(
                "serve.batch", kind="batch",
                batch_seq=self.batch_seq, n=sum(r.n for r in live),
            ) as bspan:
                if self.chaos is not None and self.chaos.active:
                    c0 = time.monotonic()
                    self.chaos.on_infer(step=self.batch_seq)
                    stall_s = time.monotonic() - c0
                if self._profiler.active:
                    # A capture is live: mark this dispatch in the
                    # xplane with the batch's trace id, so the device
                    # profile and the host span tree of the same
                    # window join on id (obs/profile).
                    import jax.profiler

                    with jax.profiler.StepTraceAnnotation(
                        STEP_MARKER, step_num=self.batch_seq,
                        program="classifier_predict",
                        jg_trace=bspan.trace_id or self.tracer.run_trace,
                    ):
                        out = np.asarray(self.predict_fn(x))
                else:
                    out = np.asarray(self.predict_fn(x))
        except Exception as e:  # any backend error must trip, not crash
            dt = time.perf_counter() - t0
            m_end = time.monotonic()
            self.breaker.record_failure(f"{type(e).__name__}: {e}")
            log.warning(
                "serve batch %d failed after %.3fs (%s: %s)",
                self.batch_seq, dt, type(e).__name__, e,
            )
            for r in live:
                self._trace_phases(r, m_pop, m_asm, stall_s, m_end)
                self._finish(
                    r, "error",
                    error=f"backend failure: {type(e).__name__}: {e}",
                    infer_s=dt, queue_s=waits[r.id],
                )
            return
        dt = time.perf_counter() - t0
        m_end = time.monotonic()
        self.batches_ctr.inc()
        self.batch_hist.observe(dt)
        if self._ledger.enabled:
            # Measured-MFU feed: one dispatch of the ONE compiled
            # program (obs/costs; the stall is chaos, not the program).
            self._ledger.observe("classifier_predict", dt - stall_s)
        if dt > self.stall_timeout_s:
            # The Tail-at-Scale stall case: the call *returned*, but so
            # late that the backend must be presumed unhealthy.
            self.breaker.record_failure(
                f"stall: batch took {dt:.3f}s > {self.stall_timeout_s}s"
            )
        else:
            self.breaker.record_success()
        offset = 0
        for r in live:
            rows = out[offset:offset + r.n]
            offset += r.n
            self._trace_phases(r, m_pop, m_asm, stall_s, m_end)
            self._finish(r, "ok", log_probs=rows, infer_s=dt,
                         queue_s=waits[r.id])
        if self.sanitizer is not None:
            # After delivery, so a trip never strands this batch's
            # clients waiting on their deadlines.
            self.sanitizer.after_step(step=self.batch_seq)

    def _trace_phases(
        self, req: Request, pop_m: float, asm_m: float,
        stall_s: float, end_m: float,
    ) -> None:
        """Bank this request's dispatch-phase child spans (assemble /
        stall / infer, explicit monotonic intervals) under its root.
        The queue child and the root's end live in ``_finish`` — the
        one place every outcome funnels through."""
        if not self.tracer.enabled or req.span is NULL_SPAN:
            return
        rec = self.tracer.record
        if asm_m > pop_m:
            rec("serve.assemble", kind="assemble", parent=req.span,
                t0=pop_m, t1=asm_m)
        if stall_s > 0:
            # The chaos/backend stall, split out of infer time so tail
            # attribution can say "p99 is stall-dominated" directly.
            rec("serve.stall", kind="stall", parent=req.span,
                t0=asm_m, t1=asm_m + stall_s, batch_seq=self.batch_seq)
        if end_m > asm_m + stall_s:
            rec("serve.infer", kind="infer", parent=req.span,
                t0=asm_m + stall_s, t1=end_m, batch_seq=self.batch_seq)

    def _finish(self, req: Request, status: str, *,
                log_probs: Optional[np.ndarray] = None, error: str = "",
                infer_s: Optional[float] = None,
                queue_s: Optional[float] = None) -> None:
        """Resolve ``req`` and emit its single ``request`` event. A
        failed claim means the waiter already abandoned it at its
        deadline — record that truth, not the late result."""
        if not req.finish(status, log_probs=log_probs, error=error):
            status = "deadline"
        self.requests_ctr.inc(status=status)
        if queue_s is None:
            queue_s = time.monotonic() - req.enqueued_at
        if self.tracer.enabled and req.span is not NULL_SPAN:
            self.tracer.record(
                "serve.queue", kind="queue", parent=req.span,
                t0=req.enqueued_at, t1=req.enqueued_at + queue_s,
            )
            # Claim-once like Request.finish: a deadline waiter that
            # already ended the root wins — this late end is a no-op.
            req.span.end(status, batch_seq=self.batch_seq)
        if self.telemetry is not None:
            fields: Dict[str, Any] = {
                "id": req.id,
                "status": status,
                "n": req.n,
                "batch_seq": self.batch_seq,
                "queue_ms": round(queue_s * 1e3, 3),
            }
            if infer_s is not None:
                fields["infer_ms"] = round(infer_s * 1e3, 3)
            if error:
                fields["error"] = error[:500]
            self.telemetry.emit("request", **fields)

    # -- lifecycle ----------------------------------------------------------

    def swap_predictor(self, predict_fn: Callable) -> None:
        """Atomic hot swap; callers warm the new fn first so serving
        never stalls on a fresh compile."""
        self.predict_fn = predict_fn

    def begin_drain(self) -> None:
        self.draining = True

    def drain(self, timeout: float = 30.0) -> bool:
        """Flush: wait for the queue to empty and the in-flight batch
        to resolve. Returns False on timeout (callers still stop)."""
        self.begin_drain()
        deadline = time.monotonic() + timeout
        while len(self.queue) or self._busy:
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.01)
        return True

    def stop(self) -> None:
        self._stop.set()
        self.queue.wake()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
