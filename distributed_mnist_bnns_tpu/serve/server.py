"""Long-running packed-inference HTTP server (stdlib-only transport).

`cli infer` walks the test split once and exits; this is the missing
long-running half of the serving story: a ``ThreadingHTTPServer`` front
end over the :class:`~.core.ServeEngine` micro-batcher, serving the
packed artifacts of ``infer.load_packed`` with the production failure
modes handled and observable (SERVING.md "Live serving"):

  POST /predict        {"images": [...], "deadline_ms": optional}
                       -> {"argmax": [...], "log_probs": [[...]]}
                       200 ok | 503 shed (queue_full/breaker_open/
                       draining) | 504 deadline | 502 backend error |
                       400 bad input | 413 batch too large
  GET  /healthz        status (ok|draining), breaker state, queue depth
  GET  /metrics        obs registry snapshot (JSON)
  POST /admin/reload   {"artifact": path} — hot swap: the new artifact
                       is loaded AND warmed off-path, then atomically
                       swapped in; unchanged weights give bitwise-
                       identical responses across the swap
  POST /admin/profile  {"duration_ms": N} — on-demand jax.profiler
                       capture of a live window, off the serving path
                       (OBSERVABILITY.md "Device profiling"); 409
                       while another capture runs

Lifecycle: SIGTERM/SIGINT install the same :class:`~..resilience.
preempt.StopRequest` pattern as training — stop admitting (new work is
shed with reason ``draining``), flush everything in flight, emit a
``drain`` event, exit 0. Crash-only discipline: the drain path is the
same code the chaos smoke exercises in CI (scripts/serve_smoke.py).
"""

from __future__ import annotations

import logging
import math
import threading
import time
from dataclasses import dataclass, field
from http.server import ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..resilience.policy import CircuitBreaker
from ..resilience.preempt import StopRequest
from .core import (
    BREAKER_TRANSITIONS_TOTAL,
    DEFAULT_TIER,
    TIERS,
    AdmissionQueue,
    Request,
    ServeEngine,
)
from .httpbase import JsonHandler

log = logging.getLogger(__name__)

# Extra slack a waiter grants the engine past the request deadline
# before abandoning (claiming) it: covers the scheduler hop between the
# engine resolving at the boundary and the waiter waking.
_WAIT_SLACK_S = 0.05

_SHED_HTTP = {
    "queue_full": 503, "breaker_open": 503, "draining": 503,
    "engine_failed": 503,
}

# Retry-After hint (seconds) on non-breaker sheds: one linger window
# plus slack — by then the queue has turned over at least one batch.
# Fractional on purpose: the in-repo clients (and the fleet router)
# parse floats; a strict HTTP client rounds up. Breaker sheds hint the
# breaker's actual remaining open time instead.
_RETRY_AFTER_S = 0.1


@dataclass
class ServeConfig:
    """Server shape + robustness budgets (CLI flags mirror these)."""

    artifact: str
    host: str = "127.0.0.1"
    port: int = 8000                 # 0 = ephemeral (tests)
    batch_size: int = 32             # the ONE compiled batch shape
    queue_depth: int = 64            # admission bound (reject past it)
    default_deadline_ms: float = 1000.0
    linger_ms: float = 2.0           # micro-batch coalescing window
    stall_timeout_s: float = 1.0     # backend call past this = failure
    breaker_threshold: int = 3       # consecutive failures to trip
    breaker_reset_s: float = 5.0     # open -> half-open timeout
    breaker_probes: int = 1          # half-open probe batches
    drain_timeout_s: float = 30.0    # flush budget on SIGTERM
    input_shape: Tuple[int, ...] = (28, 28, 1)   # warmup example shape
    telemetry_dir: Optional[str] = None
    chaos: Optional[str] = None      # RESILIENCE.md spec (or JG_CHAOS)
    seed: int = 0
    interpret: Optional[bool] = None  # None: Mosaic on TPU, else interp
    aot: bool = False                # consult the AOT executable store
                                     # (aot/, PERF.md "Cold start"):
                                     # hit = zero-compile boot + the
                                     # recompile fence armed at budget
                                     # 0 from BOOT; miss = normal
                                     # compile, re-banked for next time
    aot_dir: Optional[str] = None    # store root (default: JG_AOT_STORE
                                     # or <repo>/.jax_aot)
    trace: Optional[bool] = None     # per-request span trees into the
                                     # event log (obs/trace): True/False
                                     # explicit, None = the JG_TRACE env
                                     # var; needs telemetry_dir
    costs: Optional[bool] = None     # per-program HLO cost ledger +
                                     # measured MFU (obs/costs,
                                     # OBSERVABILITY.md "Device
                                     # profiling"): True/False explicit,
                                     # None = the JG_COSTS env var
    events_max_bytes: Optional[int] = None  # size-rotate events.jsonl
                                     # past this many bytes (obs/events
                                     # "Rotation"; None = the
                                     # JG_EVENTS_MAX_BYTES env var, else
                                     # unbounded)
    extra: Dict[str, Any] = field(default_factory=dict)


class PackedInferenceServer:
    """Owns the engine, the HTTP front end and the drain lifecycle."""

    def __init__(self, config: ServeConfig):
        self.config = config
        from ..obs import Telemetry
        from ..obs.costs import arm_ledger

        self.telemetry = Telemetry(
            config.telemetry_dir, heartbeat=False, trace=config.trace,
            events_max_bytes=config.events_max_bytes,
        )
        # Device introspection (obs/costs): the process-wide ledger;
        # an explicit flag wins over the JG_COSTS env default.
        self._ledger = arm_ledger(config.costs)
        from ..resilience.chaos import ChaosController

        self.chaos = ChaosController.from_config(
            config.chaos, seed=config.seed, telemetry=self.telemetry
        )
        self.breaker = CircuitBreaker(
            failure_threshold=config.breaker_threshold,
            reset_timeout_s=config.breaker_reset_s,
            half_open_probes=config.breaker_probes,
            on_transition=self._on_breaker_transition,
        )
        self.queue = AdmissionQueue(config.queue_depth)
        self.stop_request = StopRequest()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._reload_lock = threading.Lock()
        self._started_at = time.time()
        self.engine: Optional[ServeEngine] = None
        self.artifact_info: Dict[str, Any] = {}
        self._aot_store = None
        if config.aot:
            from ..aot import AotStore

            self._aot_store = AotStore(
                config.aot_dir, telemetry=self.telemetry
            )
        self.aot_status: Optional[str] = None
        from ..obs import get_tracker

        self._tracker = get_tracker()
        self._boot_mark: Optional[int] = None
        self._engine_sanitizer = None
        # Request-body cap: a full micro-batch of JSON floats (~32
        # chars/value incl. separators) plus headroom, floored at 1 MiB.
        # Enforced BEFORE the body is read — overload protection must
        # not be bypassable by size (reject-new over collapse).
        n_vals = 1
        for d in config.input_shape:
            n_vals *= int(d)
        self.max_body_bytes = max(
            1 << 20, config.batch_size * n_vals * 32 + (1 << 16)
        )

    # -- predictor loading ---------------------------------------------------

    def _interpret(self) -> bool:
        if self.config.interpret is not None:
            return self.config.interpret
        import jax

        return jax.default_backend() != "tpu"

    def _load_and_warm(self, path: str):
        """load_packed + one padded-shape call, OFF the serving path:
        the compile happens before the swap (or before the first
        request), so traffic never waits on XLA.

        With ``aot`` enabled the AOT store is consulted first: a hit
        deserializes the stored executable (no trace, no compile — the
        warm call below just faults the program in); a miss compiles
        exactly as before and re-banks the executable. Returns
        ``(fn, info, aot_meta)``.
        """
        if self._aot_store is not None:
            from ..aot import load_packed_aot

            fn, info, meta = load_packed_aot(
                path,
                batch_size=self.config.batch_size,
                input_shape=self.config.input_shape,
                interpret=self._interpret(),
                store=self._aot_store,
            )
        else:
            from ..infer import load_packed

            fn, info = load_packed(path, interpret=self._interpret())
            meta = {"status": "disabled"}
        if self._ledger.enabled:
            # Per-program cost ledger (obs/costs): an AOT-path fn is a
            # Compiled and is analyzed in place — no compile, so a
            # budget-0 fence stays green; the online jitted fn pays one
            # throwaway analysis compile HERE, inside the boot/reload
            # window the fence already parks around.
            import jax
            import jax.numpy as jnp

            sds = jax.ShapeDtypeStruct(
                (self.config.batch_size, *self.config.input_shape),
                jnp.float32,
            )
            self._ledger.record(
                "classifier_predict", fn, example_args=(sds,),
                telemetry=self.telemetry,
                source={"hit": "aot_hit", "miss": "aot_miss"}.get(
                    meta.get("status"), "online"
                ),
                artifact=path,
            )
        warm = np.zeros(
            (self.config.batch_size, *self.config.input_shape), np.float32
        )
        np.asarray(fn(warm))
        return fn, info, meta

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> Tuple[str, int]:
        """Load + warm the artifact, start the engine and the HTTP
        front end. Returns the bound (host, port)."""
        cfg = self.config
        # Boot mark BEFORE the artifact load: "zero compiles post-boot"
        # means from here, not from post-warmup.
        self._boot_mark = self._tracker.mark()
        fn, info, aot_meta = self._load_and_warm(cfg.artifact)
        # jg: disable=JG007 -- single-threaded startup (the HTTP front end starts below); later writes happen inside reload_artifact under _reload_lock
        self.aot_status = aot_meta.get("status")
        # jg: disable=JG007 -- same single-threaded-startup read as the write one line up
        if self.aot_status == "hit":
            # Everything came from the store: nothing is left to
            # compile, so arm the recompile fence at budget ZERO from
            # the boot mark (ROADMAP item 3's tightened contract; the
            # cold path keeps today's unfenced behavior and re-banks).
            from ..analysis.guards import Sanitizer, SanitizerConfig

            self._engine_sanitizer = Sanitizer(
                SanitizerConfig(recompile_fence=True,
                                recompile_budget=0, warmup_steps=0),
                telemetry=self.telemetry,
                registry=self.telemetry.registry,
            )
            self._engine_sanitizer.pin_baseline(self._boot_mark)
        # jg: disable=JG007 -- single-threaded startup: the HTTP front end (the only other reader) starts a few lines below; later writes go through reload_artifact under _reload_lock
        self.artifact_info = dict(info)
        self.engine = ServeEngine(
            fn,
            batch_size=cfg.batch_size,
            queue=self.queue,
            breaker=self.breaker,
            chaos=self.chaos if self.chaos.active else None,
            telemetry=self.telemetry,
            stall_timeout_s=cfg.stall_timeout_s,
            linger_s=cfg.linger_ms / 1e3,
            sanitizer=self._engine_sanitizer,
        ).start()
        server = self

        class Handler(_Handler):
            srv = server

        self._httpd = ThreadingHTTPServer((cfg.host, cfg.port), Handler)
        self._httpd.daemon_threads = True
        host, port = self._httpd.server_address[:2]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="serve-http",
            daemon=True,
        )
        self._http_thread.start()
        self.telemetry.manifest(
            config={
                "artifact": cfg.artifact,
                "batch_size": cfg.batch_size,
                "queue_depth": cfg.queue_depth,
                "default_deadline_ms": cfg.default_deadline_ms,
                "stall_timeout_s": cfg.stall_timeout_s,
                "breaker_threshold": cfg.breaker_threshold,
                "breaker_reset_s": cfg.breaker_reset_s,
                "chaos": self.chaos.spec or None,
                # jg: disable=JG007 -- benign racy read (atomic str attr): manifest records the boot-time status; reload re-writes it atomically
                "aot": self.aot_status,
                **cfg.extra,
            },
            # jg: disable=JG007 -- benign racy read: reload_artifact swaps the whole dict atomically (one STORE_ATTR), so this sees the old or the new mapping, never a torn one
            artifact_info=self.artifact_info,
        )
        log.info(
            "serving %s (%s) on %s:%d — batch %d, queue %d, deadline "
            "%.0fms", cfg.artifact, info.get("family"), host, port,
            cfg.batch_size, cfg.queue_depth, cfg.default_deadline_ms,
        )
        return host, port

    def _on_breaker_transition(
        self, old: str, new: str, reason: str
    ) -> None:
        self.telemetry.registry.counter(
            BREAKER_TRANSITIONS_TOTAL,
            "circuit-breaker state transitions",
        ).inc(to=new)
        if new == "open":
            self.telemetry.emit(
                "breaker_open", from_state=old, reason=reason
            )
        elif new == "closed":
            self.telemetry.emit(
                "breaker_close", from_state=old, reason=reason
            )
        # half_open is an internal hop; the close/open events bracket it

    def reload_artifact(self, path: Optional[str] = None) -> Dict[str, Any]:
        """Hot swap to ``path`` (default: the configured artifact —
        re-read from disk, the "a new msgpack landed under the same
        name" deployment). Load + warm happen outside the swap, so the
        worker observes either the old or the new predictor, never a
        half-built one."""
        path = path or self.config.artifact
        with self._reload_lock:  # serialize concurrent admin calls
            if self._engine_sanitizer is not None:
                # A reload that MISSES the store compiles off-path —
                # legitimately. Park the budget-0 fence on a sentinel
                # for the duration (the worker keeps serving and keeps
                # calling after_step), then re-pin to the post-reload
                # count so the zero-compile contract resumes. A reload
                # served FROM the store re-pins to an unchanged count.
                self._engine_sanitizer.pin_baseline(1 << 62)
            try:
                fn, info, aot_meta = self._load_and_warm(path)
                assert self.engine is not None
                self.engine.swap_predictor(fn)
                self.artifact_info = dict(info)
                # /healthz must describe the SERVING artifact's load,
                # not the boot's — a reload that missed the store is
                # visible (alongside the nonzero recompiles_post_boot
                # its off-path compile produced).
                self.aot_status = aot_meta.get("status")
            finally:
                if self._engine_sanitizer is not None:
                    self._engine_sanitizer.pin_baseline(
                        self._tracker.count
                    )
        # info nests under its own field: transformer artifacts carry a
        # "kind" key that would collide with the event envelope's kind.
        self.telemetry.emit("reload", artifact=path, info=dict(info),
                            aot=aot_meta.get("status"))
        log.info("hot-reloaded artifact %s (%s)", path, info.get("family"))
        return dict(info)

    def health(self) -> Dict[str, Any]:
        eng = self.engine
        if eng is not None and eng.fence_error is not None:
            status = "failed"          # load balancers must route away
        elif eng is not None and eng.draining:
            status = "draining"
        else:
            status = "ok"
        health = {
            "status": status,
            "breaker": self.breaker.state,
            "queue_depth": len(self.queue),
            "batch_size": self.config.batch_size,
            # jg: disable=JG007 -- benign racy read (atomic dict swap); taking _reload_lock here would stall /healthz behind a reload's load+warm compile, exactly a JG009 shape
            "family": self.artifact_info.get("family"),
            # jg: disable=JG007 -- benign racy read (atomic str attr swap); same rationale as family above — /healthz must not block behind a reload compile
            "aot": self.aot_status,
            "recompiles_post_boot": (
                self._tracker.count - self._boot_mark
                if self._boot_mark is not None else None
            ),
            "fence_error": eng.fence_error if eng is not None else None,
            "uptime_s": round(time.time() - self._started_at, 3),
        }
        if self._ledger.enabled:
            # Device introspection (OBSERVABILITY.md "Device
            # profiling"): the per-program cost ledger (flops/HBM +
            # measured MFU) and the live HBM census — healthz is a
            # poll-rate path, so the CPU live-buffer walk is fine here.
            from ..obs import device_memory_stats

            health["programs"] = self._ledger.snapshot()
            mem = device_memory_stats(live_fallback=True)
            if mem is not None:
                health["device_memory"] = mem
        return health

    def profile_dir_default(self) -> Optional[str]:
        """Default /admin/profile artifact dir (shared convention:
        ``<telemetry_dir>/profile``; None makes the handler require an
        explicit ``dir`` in the body)."""
        from ..obs.profile import default_capture_dir

        return default_capture_dir(self.config.telemetry_dir)

    def request_stop(self, reason: str = "stop requested") -> None:
        self.stop_request.request(reason)

    def drain_and_stop(self) -> Dict[str, Any]:
        """Stop admitting, flush in-flight work, shut the front end
        down, seal telemetry. Idempotent-ish: safe to call once after
        the run loop exits."""
        assert self.engine is not None
        t0 = time.monotonic()
        inflight = len(self.queue)
        self.engine.begin_drain()
        flushed = self.engine.drain(timeout=self.config.drain_timeout_s)
        self.engine.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        stats = {
            "reason": self.stop_request.reason or "stop requested",
            "inflight_at_drain": inflight,
            "flushed": flushed,
            "requests_total": int(self.engine.requests_ctr.total()),
            "shed_total": int(self.engine.shed_ctr.total()),
            "batches_total": int(self.engine.batches_ctr.total()),
            "wall_s": round(time.monotonic() - t0, 3),
        }
        self.telemetry.emit("drain", **stats)
        self.telemetry.close()
        log.info("drained and stopped: %s", stats)
        return stats

    def run(self) -> int:
        """CLI entry: serve until SIGTERM/SIGINT, graceful-drain, exit
        0. The handler pattern is resilience/preempt.py's — the signal
        only sets a flag; this loop polls it and runs the drain in
        normal (non-handler) context. Handlers install BEFORE
        ``start()``: a supervisor's SIGTERM during the warmup compile
        must also land as a graceful (if trivially empty) drain, not
        the default kill."""
        with self.stop_request.install():
            self.start()
            while not self.stop_request.requested:
                time.sleep(0.05)
        self.drain_and_stop()
        return 0


class _Handler(JsonHandler):
    """Per-connection handler; ``srv`` is bound by the enclosing
    server's subclass. Threaded: N handlers block in ``Request.event``
    waits while the single engine worker batches behind them. The JSON/
    body-cap/timeout plumbing is the shared :class:`~.httpbase.
    JsonHandler`."""

    srv: PackedInferenceServer
    logger = log

    def _max_body_bytes(self) -> int:
        return self.srv.max_body_bytes

    def _body_limit_error(self, n: int) -> str:
        return (f"body of {n} bytes exceeds the "
                f"{self.srv.max_body_bytes}-byte limit "
                "(one micro-batch of examples)")

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        if self.path == "/healthz":
            self._reply(200, self.srv.health())
        elif self.path == "/metrics":
            # JSON by default, Prometheus text under Accept: text/plain
            # (shared negotiation in httpbase).
            self._reply_metrics(self.srv.telemetry.registry)
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 (http.server API)
        if self.path == "/predict":
            self._predict()
        elif self.path == "/admin/reload":
            self._reload()
        elif self.path == "/admin/profile":
            # On-demand device capture (obs/profile; shared handler in
            # httpbase): this handler thread sleeps through the window,
            # traffic keeps flowing through the worker.
            self._admin_profile(
                self.srv.telemetry, self.srv.profile_dir_default()
            )
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def _reload(self) -> None:
        body = self._read_json()
        if body is None:
            return
        try:
            info = self.srv.reload_artifact(body.get("artifact"))
        except (OSError, ValueError, KeyError) as e:
            self._reply(
                400, {"error": f"reload failed: {type(e).__name__}: {e}"}
            )
            return
        self._reply(200, {"reloaded": True, "info": info})

    def _predict(self) -> None:
        body = self._read_json()
        if body is None:
            return
        engine = self.srv.engine
        assert engine is not None
        try:
            images = np.asarray(body["images"], np.float32)
        except (KeyError, TypeError, ValueError) as e:
            self._reply(400, {"error": f"bad images payload: {e}"})
            return
        expected = tuple(self.srv.config.input_shape)
        if images.ndim == len(expected):
            images = images[None]  # single unbatched example
        if images.shape[1:] != expected:
            # One compiled batch shape is the whole micro-batcher
            # contract: a differently-shaped example must be an
            # explicit 400, not a cross-request concatenate error or a
            # fresh jit signature.
            self._reply(400, {
                "error": f"per-example shape {list(images.shape[1:])} "
                         f"does not match the served input shape "
                         f"{list(expected)}",
            })
            return
        if images.shape[0] > engine.batch_size:
            self._reply(413, {
                "error": f"request batch {images.shape[0]} exceeds the "
                         f"compiled micro-batch size {engine.batch_size}",
            })
            return
        try:
            deadline_ms = float(
                body.get("deadline_ms",
                         self.srv.config.default_deadline_ms)
            )
        except (TypeError, ValueError):
            deadline_ms = float("nan")
        if not (math.isfinite(deadline_ms) and deadline_ms > 0):
            self._reply(400, {
                "error": f"deadline_ms must be a positive finite "
                         f"number, got {body.get('deadline_ms')!r}",
            })
            return
        deadline = time.monotonic() + deadline_ms / 1e3
        tier = body.get("tier", DEFAULT_TIER)
        if tier not in TIERS:
            self._reply(400, {
                "error": f"unknown tier {tier!r} (have: "
                         f"{', '.join(TIERS)})",
            })
            return
        # x-jg-trace: the client mints, this server adopts — the
        # request's span tree joins the caller's trace (obs/trace;
        # malformed headers degrade to a fresh trace, never a 4xx).
        from ..obs.trace import TRACE_HEADER, parse_header

        ctx = parse_header(self.headers.get(TRACE_HEADER))
        req = engine.submit(images, deadline, ctx, tier=tier)
        if isinstance(req, str):  # shed reason
            self._reply(_SHED_HTTP[req], {"error": "shed", "reason": req},
                        headers=self._shed_headers(req))
            return
        self._wait_and_reply(req, deadline)

    def _shed_headers(self, reason: str) -> Dict[str, str]:
        """Retry-After for every 503: the client half (serve/client
        retry-with-backoff, the fleet router) honors it instead of
        guessing. Breaker sheds hint the remaining open time — retrying
        sooner is guaranteed another fast-fail."""
        if reason == "breaker_open":
            after = max(
                self.srv.breaker.seconds_until_half_open(),
                _RETRY_AFTER_S,
            )
        else:
            after = _RETRY_AFTER_S
        return {"Retry-After": f"{after:.3f}"}

    def _trace_headers(self, req: Request) -> Optional[Dict[str, str]]:
        """Echo the request's trace id so an untraced-by-the-client
        caller can still find its span tree in the server's log."""
        from ..obs.trace import TRACE_HEADER, format_header

        ctx = req.span.context
        return {TRACE_HEADER: format_header(ctx)} if ctx else None

    def _wait_and_reply(self, req: Request, deadline: float) -> None:
        """Block until the engine resolves ``req`` or its deadline
        passes — the response ALWAYS arrives within deadline + slack,
        even if the backend is mid-stall (the abandoned request is
        claimed, so the engine discards its late result)."""
        remaining = deadline - time.monotonic() + _WAIT_SLACK_S
        if not req.event.wait(max(remaining, 0.0)):
            if req.finish("deadline", error="deadline exceeded"):
                # The waiter won the claim: it owns the root span's end
                # too (the engine's later _finish end is a no-op).
                req.span.end("deadline")
                self._reply(504, {
                    "error": "deadline exceeded", "id": req.id,
                }, headers=self._trace_headers(req))
                return
            # engine won the race after our timeout check: fall through
        status = req.status
        m_resp = time.monotonic()
        trace_headers = self._trace_headers(req)
        if status == "ok":
            lp = req.log_probs
            assert lp is not None
            # No request id in the OK body: responses are a pure
            # function of (weights, images), which is what makes the
            # hot-reload bitwise-identity contract assertable.
            self._reply(200, {
                "argmax": [int(i) for i in lp.argmax(-1)],
                "log_probs": [[float(v) for v in row] for row in lp],
            }, headers=trace_headers)
        elif status == "deadline":
            self._reply(504, {"error": req.error or "deadline exceeded",
                              "id": req.id}, headers=trace_headers)
        elif status == "breaker_open":
            self._reply(503, {"error": "shed", "reason": "breaker_open",
                              "id": req.id},
                        headers={**(trace_headers or {}),
                                 **self._shed_headers("breaker_open")})
        elif status == "shed":
            # Queue-displaced by a higher-tier admission (core.py
            # put_or_displace): an explicit low-tier shed, not an error.
            self._reply(503, {"error": "shed", "reason": "displaced",
                              "tier": req.tier, "id": req.id},
                        headers={**(trace_headers or {}),
                                 **self._shed_headers("displaced")})
        else:
            self._reply(502, {"error": req.error or "backend failure",
                              "id": req.id}, headers=trace_headers)
        engine = self.srv.engine
        if engine is not None and engine.tracer.enabled:
            # The handler-side tail of the tree: wake-to-reply-written
            # (serialization + socket write), the "respond" phase of
            # admit -> queue -> dispatch -> respond.
            engine.tracer.record(
                "serve.respond", kind="respond", parent=req.span,
                t0=m_resp, t1=time.monotonic(), status=str(status),
            )
