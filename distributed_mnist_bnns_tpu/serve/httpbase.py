"""Shared stdlib-HTTP handler plumbing for the serving front ends.

Both servers (serve/server.py classifier micro-batcher, serve/lm/
streaming generation) speak JSON over ``ThreadingHTTPServer``; the
request/response mechanics that must not drift between them live here:
keep-alive HTTP/1.1 with a connection-socket timeout (a client that
declares a Content-Length and never sends the body must not pin a
handler thread forever), stderr chatter routed into logging, one
``_reply`` shape, a body-size cap enforced BEFORE the body is read
(overload protection must not be bypassable by size; replying without
reading desyncs a keep-alive connection, so an oversize request closes
it), and the shared ``/metrics`` content negotiation: JSON by default,
Prometheus text exposition under ``Accept: text/plain`` — one scrape
format for the whole replica fleet (OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import logging
from http.server import BaseHTTPRequestHandler
from typing import Any, Dict, Optional

_log = logging.getLogger(__name__)

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class JsonHandler(BaseHTTPRequestHandler):
    """JSON request/response base; subclasses bind their server object
    and override ``_max_body_bytes`` / ``logger`` as needed."""

    protocol_version = "HTTP/1.1"
    timeout = 30.0
    logger = _log

    # route BaseHTTPRequestHandler's stderr chatter into logging
    def log_message(self, fmt: str, *args: Any) -> None:
        self.logger.debug("http: " + fmt, *args)

    def _max_body_bytes(self) -> int:
        return 1 << 20

    def _body_limit_error(self, n: int) -> str:
        return (f"body of {n} bytes exceeds the "
                f"{self._max_body_bytes()}-byte limit")

    def _reply(
        self, code: int, payload: Dict[str, Any],
        headers: Optional[Dict[str, str]] = None,
    ) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _reply_metrics(self, registry: Any) -> None:
        """``GET /metrics`` for both serving engines: the registry
        snapshot as JSON (the default, what the repo's own tooling
        reads), or Prometheus text exposition when the client asks for
        ``text/plain`` — fleet scrapers negotiate, nothing breaks."""
        accept = self.headers.get("Accept", "")
        if "text/plain" not in accept:
            self._reply(200, registry.snapshot())
            return
        from ..obs import render_prometheus

        body = render_prometheus(registry.snapshot()).encode()
        self.send_response(200)
        self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _admin_profile(
        self, telemetry: Any, default_dir: Optional[str],
    ) -> None:
        """``POST /admin/profile {"duration_ms": N, "dir": optional}``
        for both serving front ends (OBSERVABILITY.md "Device
        profiling"): arm an on-demand ``jax.profiler`` capture for the
        window, off the serving path (THIS handler thread sleeps
        through it; the engine worker never blocks), and reply with the
        artifact dir + sizes. One capture per process: a concurrent
        request gets 409."""
        from ..obs.profile import ProfileBusyError, get_profiler

        body = self._read_json()
        if body is None:
            return
        try:
            duration_ms = float(body.get("duration_ms", 1000.0))
        except (TypeError, ValueError):
            duration_ms = float("nan")
        if not duration_ms > 0:   # also catches NaN
            self._reply(400, {
                "error": "duration_ms must be a positive number, got "
                         f"{body.get('duration_ms')!r}",
            })
            return
        artifact_dir = body.get("dir") or default_dir
        if not artifact_dir:
            self._reply(400, {
                "error": "no artifact dir: pass {\"dir\": ...} or run "
                         "the server with --telemetry-dir",
            })
            return
        try:
            summary = get_profiler().capture(
                duration_ms, artifact_dir=str(artifact_dir),
                telemetry=telemetry,
            )
        except ProfileBusyError as e:
            self._reply(409, {"error": str(e)})
            return
        except (OSError, RuntimeError, ValueError) as e:
            self._reply(500, {
                "error": f"capture failed: {type(e).__name__}: {e}",
            })
            return
        self._reply(200, summary)

    def _read_json(self) -> Optional[Dict[str, Any]]:
        try:
            n = int(self.headers.get("Content-Length", 0))
            if n > self._max_body_bytes():
                # replying without reading the body desyncs a keep-
                # alive connection — close it instead of draining GBs
                self.close_connection = True
                self._reply(413, {"error": self._body_limit_error(n)})
                return None
            return json.loads(self.rfile.read(n) or b"{}")
        except (ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"bad request body: {e}"})
            return None
