"""Importable classifier-serving saturation harness (ROADMAP item 5).

bench.py's ``--serving-bench`` section measures the frozen predictor's
raw throughput; the perf gate needs something different — the ENGINE's
latency behavior under load: request p99 through the real admission
queue, micro-batcher and deadline machinery, at saturation, in-process
(no HTTP, no subprocess), deterministic enough to band in
``PERF_BASELINES.json``. This module is that measurement, lifted out of
bench.py so ``bench.py --serve-p99-bench``, ``scripts/perf_gate.py``
and any future router/autoscaler test all run the SAME code path —
the banked ceiling and the number a PR is judged by can never drift
apart.

The band discipline mirrors the PR 10 step-time ceilings: CPU latency
under thread scheduling jitter swings run to run, so the gate's
tolerance is WIDE (a catastrophe detector for e.g. a lock held across
the predictor dispatch or a per-request host-work leak — which
multiplies p99, not jitters it), while shed accounting and the
zero-failure invariant stay exact.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List

import numpy as np

from ..obs.trace import percentile as _percentile
from ..resilience.policy import CircuitBreaker
from .core import AdmissionQueue, ServeEngine


def make_tiny_packed_predictor(
    batch_size: int = 8, *, interpret: bool = True, seed: int = 0,
):
    """A small packed bnn-mlp predictor built in-process (no disk
    artifact) — the cheapest real thing the serving engine can
    dispatch. Returns ``(predict_fn, input_shape)``; the warmup call at
    the compiled batch shape has already been paid."""
    import jax

    from ..infer import freeze_bnn_mlp
    from ..models import bnn_mlp_small

    model = bnn_mlp_small(backend="xla")
    x = jax.random.normal(jax.random.PRNGKey(seed), (2, 28, 28, 1))
    variables = model.init(
        {"params": jax.random.PRNGKey(seed),
         "dropout": jax.random.PRNGKey(seed + 1)},
        x, train=True,
    )
    fn, _info = freeze_bnn_mlp(model, variables, interpret=interpret)
    warm = np.zeros((batch_size, 28, 28, 1), np.float32)
    np.asarray(fn(warm))
    return fn, (28, 28, 1)


def saturation_probe(
    predict_fn,
    *,
    batch_size: int = 8,
    input_shape=(28, 28, 1),
    n_threads: int = 8,
    duration_s: float = 2.0,
    deadline_ms: float = 2000.0,
    queue_depth: int = 16,
    linger_ms: float = 1.0,
    chaos: Any = None,
    telemetry: Any = None,
) -> Dict[str, Any]:
    """Drive a :class:`~.core.ServeEngine` at saturation and measure
    request-level latency percentiles.

    ``n_threads`` submitter threads each keep one single-example
    request in flight back to back for ``duration_s`` — with
    ``n_threads >= batch_size`` the queue never runs dry, so the
    reported p99 covers queue wait + batch assembly + dispatch, i.e.
    the number a client actually experiences under load (the
    Tail-at-Scale quantity, not the predictor's solo latency)."""
    breaker = CircuitBreaker(
        failure_threshold=1 << 30,  # measurement, not resilience
        reset_timeout_s=3600.0,
    )
    queue = AdmissionQueue(queue_depth)
    engine = ServeEngine(
        predict_fn,
        batch_size=batch_size,
        queue=queue,
        breaker=breaker,
        chaos=chaos,
        telemetry=telemetry,
        stall_timeout_s=3600.0,
        linger_s=linger_ms / 1e3,
    ).start()
    latencies: List[float] = []
    outcomes: Dict[str, int] = {}
    lock = threading.Lock()
    stop_at = time.monotonic() + duration_s

    def hammer(tid: int) -> None:
        rng = np.random.RandomState(tid)
        images = rng.randn(1, *input_shape).astype(np.float32)
        while time.monotonic() < stop_at:
            t0 = time.monotonic()
            req = engine.submit(
                images, time.monotonic() + deadline_ms / 1e3
            )
            if isinstance(req, str):   # shed
                with lock:
                    outcomes[req] = outcomes.get(req, 0) + 1
                time.sleep(0.001)      # back off a hair, stay saturated
                continue
            req.event.wait(deadline_ms / 1e3 + 1.0)
            dt = time.monotonic() - t0
            with lock:
                outcomes[req.status or "lost"] = (
                    outcomes.get(req.status or "lost", 0) + 1
                )
                if req.status == "ok":
                    latencies.append(dt)

    threads = [
        threading.Thread(target=hammer, args=(i,), daemon=True)
        for i in range(n_threads)
    ]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + deadline_ms / 1e3 + 30.0)
    wall = time.monotonic() - t_start
    engine.begin_drain()
    engine.drain(timeout=10.0)
    engine.stop()
    latencies.sort()
    ok = len(latencies)
    return {
        "n_threads": n_threads,
        "batch_size": batch_size,
        "queue_depth": queue_depth,
        "duration_s": round(wall, 3),
        "requests_ok": ok,
        "outcomes": outcomes,
        "throughput_rps": round(ok / wall, 1) if wall > 0 else None,
        "p50_ms": (
            round(_percentile(latencies, 50.0) * 1e3, 3) if ok else None
        ),
        "p90_ms": (
            round(_percentile(latencies, 90.0) * 1e3, 3) if ok else None
        ),
        "p99_ms": (
            round(_percentile(latencies, 99.0) * 1e3, 3) if ok else None
        ),
        "batches": engine.batch_seq,
    }


def serving_p99_section(
    *,
    batch_size: int = 8,
    n_threads: int = 8,
    duration_s: float = 2.0,
    interpret: bool = True,
    seed: int = 0,
    telemetry: Any = None,
) -> Dict[str, Any]:
    """The bench-record-shaped section (``serving_p99``): tiny packed
    model, saturated engine, exact percentiles — what
    ``scripts/perf_gate.py`` bands as ``classifier_p99_under_
    saturation_ms`` (wide tolerance, catastrophe detector).

    ``telemetry``: an optional obs Telemetry whose event log the
    engine's request events and span trees land in — the perf gate
    passes a traced one so a tripped serving band can EXPLAIN itself
    via `cli trace` tail attribution over this probe's events."""
    fn, input_shape = make_tiny_packed_predictor(
        batch_size, interpret=interpret, seed=seed
    )
    out = saturation_probe(
        fn,
        batch_size=batch_size,
        input_shape=input_shape,
        n_threads=n_threads,
        duration_s=duration_s,
        telemetry=telemetry,
    )
    out["interpret_mode"] = interpret
    return out
