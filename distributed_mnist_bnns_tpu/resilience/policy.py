"""Retry policy: jittered backoff + transient/fatal classification.

The replacement for the constant-backoff retry-everything loop of
``utils/recovery.py`` (kept as a compat shim over this module):

  * **classification** — a dataset ``FileNotFoundError`` will fail the
    same way 100 times; retrying it burns the budget and hides the real
    error. Config/programming errors fail fast; IO/chaos/unknown
    runtime faults retry; :class:`~.preempt.Preempted` resumes without
    consuming the failure budget (preemption is the *common case* on a
    TPU fleet, not a failure).
  * **jittered exponential backoff** — constant-delay retries from a
    fleet of restarting workers synchronize into thundering herds on
    whatever shared service failed (filesystem, coordinator);
    ``base * factor**n`` capped at ``max_backoff_s``, with the top
    ``jitter`` fraction uniformly randomized, decorrelates them.
  * **structured events** — every restart lands in the obs event log
    (``restart`` kind, ``restarts_total`` counter) so a run that
    limped through N retries is distinguishable from a clean one.
"""

from __future__ import annotations

import logging
import random
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Tuple, TypeVar

from .chaos import ChaosFault
from .preempt import Preempted

log = logging.getLogger(__name__)

T = TypeVar("T")

RESTARTS_TOTAL = "restarts_total"

# Exceptions that restarting cannot fix: bad config, missing datasets,
# programming errors. KeyboardInterrupt/SystemExit are handled apart
# (never retried, never wrapped).
DEFAULT_FATAL_TYPES: Tuple[type, ...] = (
    FileNotFoundError,
    NotADirectoryError,
    IsADirectoryError,
    PermissionError,
    ValueError,
    TypeError,
    AttributeError,
    KeyError,
    IndexError,
    ImportError,
    NotImplementedError,
    AssertionError,
)


class TrainingFailure(RuntimeError):
    """Raised when training keeps failing past the retry budget."""


def classify_failure(
    exc: BaseException,
    *,
    fatal_types: Tuple[type, ...] = DEFAULT_FATAL_TYPES,
    transient_types: Tuple[type, ...] = (),
) -> str:
    """``"preempt"`` | ``"transient"`` | ``"fatal"``.

    ``transient_types`` wins over ``fatal_types`` (an overridable
    escape hatch: e.g. a caller whose dataset lives on a flaky NFS
    mount may declare ``FileNotFoundError`` transient). Unknown
    exceptions default to transient — the pre-policy behavior retried
    everything, and an IO stack can surface almost any type."""
    if isinstance(exc, Preempted):
        return "preempt"
    if isinstance(exc, ChaosFault):
        return "transient"
    if transient_types and isinstance(exc, transient_types):
        return "transient"
    if isinstance(exc, (KeyboardInterrupt, SystemExit, GeneratorExit)):
        return "fatal"
    if isinstance(exc, fatal_types):
        return "fatal"
    return "transient"


@dataclass
class RetryPolicy:
    """Restart budget + backoff shape + classification overrides."""

    max_restarts: int = 2          # transient-failure budget
    max_preemptions: int = 64      # graceful-stop resumes (separate:
                                   # preemption is routine, not failure)
    base_backoff_s: float = 1.0
    backoff_factor: float = 2.0
    max_backoff_s: float = 60.0
    jitter: float = 0.5            # top fraction of the delay randomized
    seed: Optional[int] = None     # None: nondeterministic jitter
    fatal_types: Tuple[type, ...] = DEFAULT_FATAL_TYPES
    transient_types: Tuple[type, ...] = ()
    _rng: random.Random = field(init=False, repr=False)

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)

    def classify(self, exc: BaseException) -> str:
        return classify_failure(
            exc,
            fatal_types=self.fatal_types,
            transient_types=self.transient_types,
        )

    def backoff(self, attempt: int) -> float:
        """Delay before retry ``attempt`` (1-based): capped exponential,
        uniformly jittered over the top ``jitter`` fraction."""
        raw = min(
            self.base_backoff_s * self.backoff_factor ** (attempt - 1),
            self.max_backoff_s,
        )
        if raw <= 0:
            return 0.0
        floor = raw * (1.0 - min(max(self.jitter, 0.0), 1.0))
        return floor + self._rng.random() * (raw - floor)


def _note_restart(
    telemetry: Any, *, cause: str, attempt: int,
    error: BaseException, backoff_s: float,
) -> None:
    from ..obs import default_registry  # lazy: keep import-time light

    registry = (
        telemetry.registry if telemetry is not None else default_registry()
    )
    registry.counter(
        RESTARTS_TOTAL, "resilient-loop trainer restarts"
    ).inc(cause=cause)
    if telemetry is not None:
        telemetry.emit(
            "restart", cause=cause, attempt=attempt,
            error_type=type(error).__name__, error=str(error)[:500],
            backoff_s=round(backoff_s, 3),
        )


def run_with_policy(
    make_trainer: Callable[[], Any],
    run: Callable[[Any], T],
    *,
    policy: Optional[RetryPolicy] = None,
    telemetry: Any = None,
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """Execute ``run(make_trainer())`` under the retry policy.

    On a transient failure the trainer is rebuilt (with
    ``TrainConfig.resume=True`` that restores the latest good
    checkpoint generation — utils/checkpoint.py verifies digests and
    rolls back past corrupt ones) and the run retried after a jittered
    backoff, up to ``policy.max_restarts``. A :class:`Preempted` exit
    restarts immediately and counts against ``max_preemptions`` only.
    Fatal failures re-raise at once.

    ``telemetry``: an optional obs Telemetry whose event sink receives
    the ``restart`` events; pass one sharing the run's telemetry dir so
    the attempts interleave into the same ``events.jsonl`` the trainers
    append to (each trainer seals its own log before this loop emits).
    """
    policy = policy if policy is not None else RetryPolicy()
    failures = 0
    preemptions = 0
    while True:
        trainer = make_trainer()
        try:
            return run(trainer)
        except Preempted as e:
            preemptions += 1
            if preemptions > policy.max_preemptions:
                raise TrainingFailure(
                    f"preempted {preemptions} times; giving up"
                ) from e
            _note_restart(
                telemetry, cause="preemption", attempt=preemptions,
                error=e, backoff_s=0.0,
            )
            log.warning(
                "resuming after preemption %d/%d (%s)",
                preemptions, policy.max_preemptions, e,
            )
        except BaseException as e:
            kind = policy.classify(e)
            if kind == "fatal":
                log.error(
                    "fatal failure (%s: %s); not retrying",
                    type(e).__name__, e,
                )
                raise
            failures += 1
            if failures > policy.max_restarts:
                raise TrainingFailure(
                    f"training failed {failures} times; giving up"
                ) from e
            delay = policy.backoff(failures)
            _note_restart(
                telemetry, cause="transient", attempt=failures,
                error=e, backoff_s=delay,
            )
            log.warning(
                "training attempt %d/%d failed (%s: %s); restarting from "
                "latest checkpoint in %.2fs",
                failures, policy.max_restarts, type(e).__name__, e, delay,
            )
            sleep(delay)
