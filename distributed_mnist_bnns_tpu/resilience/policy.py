"""Retry policy: jittered backoff + transient/fatal classification.

The replacement for the constant-backoff retry-everything loop of
``utils/recovery.py`` (kept as a compat shim over this module):

  * **classification** — a dataset ``FileNotFoundError`` will fail the
    same way 100 times; retrying it burns the budget and hides the real
    error. Config/programming errors fail fast; IO/chaos/unknown
    runtime faults retry; :class:`~.preempt.Preempted` resumes without
    consuming the failure budget (preemption is the *common case* on a
    TPU fleet, not a failure).
  * **jittered exponential backoff** — constant-delay retries from a
    fleet of restarting workers synchronize into thundering herds on
    whatever shared service failed (filesystem, coordinator);
    ``base * factor**n`` capped at ``max_backoff_s``, with the top
    ``jitter`` fraction uniformly randomized, decorrelates them.
  * **structured events** — every restart lands in the obs event log
    (``restart`` kind, ``restarts_total`` counter) so a run that
    limped through N retries is distinguishable from a clean one.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Tuple, TypeVar

from .chaos import ChaosFault
from .preempt import Preempted

log = logging.getLogger(__name__)

T = TypeVar("T")

RESTARTS_TOTAL = "restarts_total"

# Exceptions that restarting cannot fix: bad config, missing datasets,
# programming errors. KeyboardInterrupt/SystemExit are handled apart
# (never retried, never wrapped).
DEFAULT_FATAL_TYPES: Tuple[type, ...] = (
    FileNotFoundError,
    NotADirectoryError,
    IsADirectoryError,
    PermissionError,
    ValueError,
    TypeError,
    AttributeError,
    KeyError,
    IndexError,
    ImportError,
    NotImplementedError,
    AssertionError,
)


class TrainingFailure(RuntimeError):
    """Raised when training keeps failing past the retry budget."""


def trainer_topology(trainer: Any) -> Tuple[int, dict]:
    """``(data-parallel world size, {mesh axis: size})`` of a trainer's
    mesh (no mesh → ``(1, {})``). Recorded on restart/resume/remesh
    events and in checkpoint meta so post-incident forensics can see
    whether a restore changed topology (OBSERVABILITY.md) — before
    this, a restore that silently came back on a different mesh was
    indistinguishable from a plain resume in the event log."""
    from ..parallel.remesh import mesh_topology  # lazy: import cycle

    mh = getattr(trainer, "_mh", None)
    if mh:
        # Multihost elastic rank: the world is the host count (each rank
        # is a single-process jax runtime with no in-process mesh) —
        # checkpoint meta must record it so post-incident forensics see
        # the shrink/regrow, exactly like an in-process mesh change.
        return int(mh["hosts"]), {"host": int(mh["hosts"])}
    return mesh_topology(getattr(trainer, "mesh", None))


def classify_failure(
    exc: BaseException,
    *,
    fatal_types: Tuple[type, ...] = DEFAULT_FATAL_TYPES,
    transient_types: Tuple[type, ...] = (),
) -> str:
    """``"preempt"`` | ``"transient"`` | ``"fatal"``.

    ``transient_types`` wins over ``fatal_types`` (an overridable
    escape hatch: e.g. a caller whose dataset lives on a flaky NFS
    mount may declare ``FileNotFoundError`` transient). Unknown
    exceptions default to transient — the pre-policy behavior retried
    everything, and an IO stack can surface almost any type."""
    if isinstance(exc, Preempted):
        return "preempt"
    if isinstance(exc, ChaosFault):
        return "transient"
    if transient_types and isinstance(exc, transient_types):
        return "transient"
    if isinstance(exc, (KeyboardInterrupt, SystemExit, GeneratorExit)):
        return "fatal"
    if isinstance(exc, fatal_types):
        return "fatal"
    return "transient"


@dataclass
class RetryPolicy:
    """Restart budget + backoff shape + classification overrides."""

    max_restarts: int = 2          # transient-failure budget
    max_preemptions: int = 64      # graceful-stop resumes (separate:
                                   # preemption is routine, not failure)
    base_backoff_s: float = 1.0
    backoff_factor: float = 2.0
    max_backoff_s: float = 60.0
    jitter: float = 0.5            # top fraction of the delay randomized
    seed: Optional[int] = None     # None: nondeterministic jitter
    fatal_types: Tuple[type, ...] = DEFAULT_FATAL_TYPES
    transient_types: Tuple[type, ...] = ()
    _rng: random.Random = field(init=False, repr=False)

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)

    def classify(self, exc: BaseException) -> str:
        return classify_failure(
            exc,
            fatal_types=self.fatal_types,
            transient_types=self.transient_types,
        )

    def backoff(self, attempt: int) -> float:
        """Delay before retry ``attempt`` (1-based): capped exponential,
        uniformly jittered over the top ``jitter`` fraction."""
        raw = min(
            self.base_backoff_s * self.backoff_factor ** (attempt - 1),
            self.max_backoff_s,
        )
        if raw <= 0:
            return 0.0
        floor = raw * (1.0 - min(max(self.jitter, 0.0), 1.0))
        return floor + self._rng.random() * (raw - floor)


def _note_restart(
    telemetry: Any, *, cause: str, attempt: int,
    error: BaseException, backoff_s: float, trainer: Any = None,
) -> None:
    from ..obs import default_registry  # lazy: keep import-time light

    registry = (
        telemetry.registry if telemetry is not None else default_registry()
    )
    registry.counter(
        RESTARTS_TOTAL, "resilient-loop trainer restarts"
    ).inc(cause=cause)
    if telemetry is not None:
        # Mesh topology of the attempt that failed: restore forensics
        # must be able to tell whether a later restore changed it.
        world_size, mesh_shape = trainer_topology(trainer)
        telemetry.emit(
            "restart", cause=cause, attempt=attempt,
            error_type=type(error).__name__, error=str(error)[:500],
            backoff_s=round(backoff_s, 3),
            world_size=world_size, mesh_shape=mesh_shape,
        )


def handle_preemption(
    e: "Preempted", *, policy: RetryPolicy, preemptions: int,
    telemetry: Any, trainer: Any,
) -> int:
    """Shared graceful-resume bookkeeping for the retry supervisors
    (``run_with_policy`` and ``elastic.run_elastic``): budget check,
    ``restart`` event, log line. Returns the new preemption count;
    raises :class:`TrainingFailure` past the budget."""
    preemptions += 1
    if preemptions > policy.max_preemptions:
        raise TrainingFailure(
            f"preempted {preemptions} times; giving up"
        ) from e
    _note_restart(
        telemetry, cause="preemption", attempt=preemptions,
        error=e, backoff_s=0.0, trainer=trainer,
    )
    log.warning(
        "resuming after preemption %d/%d (%s)",
        preemptions, policy.max_preemptions, e,
    )
    return preemptions


def handle_failure(
    e: BaseException, *, policy: RetryPolicy, failures: int,
    telemetry: Any, trainer: Any,
    sleep: Callable[[float], None] = time.sleep, context: str = "",
) -> int:
    """Shared transient/fatal handling for the retry supervisors:
    classify, budget, jittered backoff, ``restart`` event. Returns the
    new failure count; re-raises fatal errors immediately and raises
    :class:`TrainingFailure` past the budget. Must be called from the
    ``except`` block handling ``e`` (the fatal path re-raises the
    active exception)."""
    kind = policy.classify(e)
    if kind == "fatal":
        log.error(
            "fatal failure (%s: %s); not retrying", type(e).__name__, e,
        )
        raise e
    failures += 1
    if failures > policy.max_restarts:
        raise TrainingFailure(
            f"training failed {failures} times; giving up"
        ) from e
    delay = policy.backoff(failures)
    _note_restart(
        telemetry, cause="transient", attempt=failures,
        error=e, backoff_s=delay, trainer=trainer,
    )
    log.warning(
        "training attempt %d/%d failed (%s: %s); restarting from "
        "latest checkpoint%s in %.2fs",
        failures, policy.max_restarts, type(e).__name__, e, context,
        delay,
    )
    sleep(delay)
    return failures


def run_with_policy(
    make_trainer: Callable[[], Any],
    run: Callable[[Any], T],
    *,
    policy: Optional[RetryPolicy] = None,
    telemetry: Any = None,
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """Execute ``run(make_trainer())`` under the retry policy.

    On a transient failure the trainer is rebuilt (with
    ``TrainConfig.resume=True`` that restores the latest good
    checkpoint generation — utils/checkpoint.py verifies digests and
    rolls back past corrupt ones) and the run retried after a jittered
    backoff, up to ``policy.max_restarts``. A :class:`Preempted` exit
    restarts immediately and counts against ``max_preemptions`` only.
    Fatal failures re-raise at once.

    ``telemetry``: an optional obs Telemetry whose event sink receives
    the ``restart`` events; pass one sharing the run's telemetry dir so
    the attempts interleave into the same ``events.jsonl`` the trainers
    append to (each trainer seals its own log before this loop emits).
    """
    policy = policy if policy is not None else RetryPolicy()
    failures = 0
    preemptions = 0
    while True:
        trainer = make_trainer()
        try:
            return run(trainer)
        except Preempted as e:
            preemptions = handle_preemption(
                e, policy=policy, preemptions=preemptions,
                telemetry=telemetry, trainer=trainer,
            )
        except BaseException as e:
            failures = handle_failure(
                e, policy=policy, failures=failures,
                telemetry=telemetry, trainer=trainer, sleep=sleep,
            )


class CircuitBreaker:
    """Closed → open → half-open failure fence around a flaky dependency.

    Retrying into a dependency that is *down* is worse than failing: every
    caller blocks for a full timeout, queues behind it collapse, and the
    dependency gets hammered exactly when it needs slack (Dean & Barroso,
    "The Tail at Scale"). The breaker converts that into fast-fail:

      closed     normal operation; ``failure_threshold`` CONSECUTIVE
                 ``record_failure`` calls trip it open (any success
                 resets the streak)
      open       ``allow()`` returns False — callers fail fast without
                 touching the dependency — until ``reset_timeout_s`` has
                 elapsed
      half-open  the first ``allow()`` after the timeout transitions here
                 and admits up to ``half_open_probes`` probe calls; all
                 probes succeeding closes the breaker, any probe failure
                 re-opens it (and restarts the timeout)

    Thread-safe: the serving engine, admission path and health endpoint
    read/write concurrently. ``clock`` is injectable for tests.
    ``on_transition(old, new, reason)`` fires outside the lock after any
    state change — the serving layer uses it to emit ``breaker_open`` /
    ``breaker_close`` obs events; training restart loops can wrap a flaky
    coordinator or filesystem in the same object.

    Used by serve/ around the packed-predictor call (a stall past the
    stall budget counts as a failure, not only an exception); exposed
    here rather than in serve/ so the training path can reuse it.
    """

    def __init__(
        self,
        *,
        failure_threshold: int = 5,
        reset_timeout_s: float = 30.0,
        half_open_probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[str, str, str], None]] = None,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self.half_open_probes = max(int(half_open_probes), 1)
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probes_issued = 0
        self._probe_successes = 0

    @property
    def state(self) -> str:
        """``"closed"`` | ``"open"`` | ``"half_open"`` (point-in-time;
        an elapsed open breaker still reads "open" until the next
        ``allow()`` issues the probe)."""
        with self._lock:
            return self._state

    def _set(self, new: str, reason: str):
        """Lock held. Returns the deferred transition callback."""
        old, self._state = self._state, new
        log.warning("circuit breaker %s -> %s (%s)", old, new, reason)
        cb = self._on_transition
        return (lambda: cb(old, new, reason)) if cb is not None else None

    def seconds_until_half_open(self) -> float:
        """Remaining open time before the next ``allow()`` may issue a
        half-open probe; 0.0 unless open with the timeout still
        running. The serving layer turns this into the ``Retry-After``
        hint on breaker-shed 503s — a client that retries sooner is
        guaranteed another fast-fail."""
        with self._lock:
            if self._state != "open":
                return 0.0
            remaining = self.reset_timeout_s - (
                self._clock() - self._opened_at
            )
            return max(remaining, 0.0)

    def admits(self) -> bool:
        """Read-only admission check: False only while open with the
        reset timeout still running. Unlike ``allow()`` this never
        consumes a half-open probe slot, so the admission path can
        fast-fail queued-up work without starving the probe that the
        worker's ``allow()`` must issue."""
        with self._lock:
            return not (
                self._state == "open"
                and self._clock() - self._opened_at < self.reset_timeout_s
            )

    def allow(self) -> bool:
        """May this call proceed? Performs the open → half-open
        transition once the reset timeout elapses; in half-open, admits
        at most ``half_open_probes`` calls."""
        notify = None
        with self._lock:
            if self._state == "closed":
                allowed = True
            elif self._state == "open":
                if self._clock() - self._opened_at >= self.reset_timeout_s:
                    notify = self._set("half_open", "reset timeout elapsed")
                    self._probes_issued = 1
                    self._probe_successes = 0
                    allowed = True
                else:
                    allowed = False
            else:  # half_open
                allowed = self._probes_issued < self.half_open_probes
                if allowed:
                    self._probes_issued += 1
        if notify is not None:
            notify()
        return allowed

    def record_success(self) -> None:
        notify = None
        with self._lock:
            if self._state == "closed":
                self._consecutive_failures = 0
            elif self._state == "half_open":
                self._probe_successes += 1
                if self._probe_successes >= self.half_open_probes:
                    notify = self._set(
                        "closed",
                        f"{self._probe_successes} probe(s) succeeded",
                    )
                    self._consecutive_failures = 0
        if notify is not None:
            notify()

    def record_failure(self, reason: str = "") -> None:
        notify = None
        with self._lock:
            if self._state == "half_open":
                notify = self._set(
                    "open", reason or "half-open probe failed"
                )
                self._opened_at = self._clock()
            elif self._state == "closed":
                self._consecutive_failures += 1
                if self._consecutive_failures >= self.failure_threshold:
                    notify = self._set(
                        "open",
                        reason
                        or f"{self._consecutive_failures} consecutive "
                           "failures",
                    )
                    self._opened_at = self._clock()
            # already open: stay open; the timeout keeps its epoch so a
            # herd of late failures cannot push recovery out forever.
        if notify is not None:
            notify()
