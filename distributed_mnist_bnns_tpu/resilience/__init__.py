"""resilience — fault injection, preemption handling and retry policy.

Production TPU fleets live with preemption and partial failure as the
common case; this package supplies both halves of surviving them:

  chaos    scripted, seed-deterministic fault injection (the harness
           that *proves* the recovery machinery works — the find-then-
           fence pattern of analysis/, applied to process/IO/state
           faults instead of JAX footguns)
  preempt  SIGTERM/SIGINT-driven graceful stop at a step boundary,
           with a distinct resumable exit code
  policy   jittered-exponential retry with transient-vs-fatal
           classification and a per-run restart budget, plus the
           closed/open/half-open ``CircuitBreaker`` the packed-serving
           engine (serve/) wraps around its predictor calls
  elastic  in-process elastic data-parallel membership: chaos
           ``worker_lost``/``worker_restore`` drive a mesh shrink/grow
           with state re-placement from the newest digest-verified
           checkpoint generation (parallel/remesh) instead of a
           full-job restart

The trainer wires chaos + preempt through ``TrainConfig.chaos`` /
``--chaos`` / ``JG_CHAOS`` and ``handle_preemption``; the retry loop is
``run_with_policy`` (``utils/recovery.run_with_recovery`` is the thin
compat shim). Checkpoint integrity (content digests, generation
rollback) lives with the writers in utils/checkpoint.py. See
RESILIENCE.md for the fault catalog, spec grammar and event schema.
"""

from .chaos import (
    HOST_KINDS,
    MEMBERSHIP_KINDS,
    ChaosController,
    ChaosFault,
    ChaosInferError,
    ChaosIOError,
    ChaosStepFault,
    FaultRule,
    parse_chaos_spec,
    reset_fire_counts,
)
from .elastic import MembershipView, run_elastic
from .multihost import (
    HostMembershipView,
    read_membership,
    run_elastic_multihost,
)
from .policy import (
    DEFAULT_FATAL_TYPES,
    CircuitBreaker,
    RetryPolicy,
    TrainingFailure,
    classify_failure,
    run_with_policy,
    trainer_topology,
)
from .preempt import PREEMPT_EXIT_CODE, Preempted, StopRequest

__all__ = [
    "HOST_KINDS",
    "MEMBERSHIP_KINDS",
    "ChaosController",
    "ChaosFault",
    "ChaosInferError",
    "ChaosIOError",
    "ChaosStepFault",
    "CircuitBreaker",
    "DEFAULT_FATAL_TYPES",
    "FaultRule",
    "HostMembershipView",
    "MembershipView",
    "PREEMPT_EXIT_CODE",
    "Preempted",
    "RetryPolicy",
    "StopRequest",
    "TrainingFailure",
    "classify_failure",
    "parse_chaos_spec",
    "read_membership",
    "reset_fire_counts",
    "run_elastic",
    "run_elastic_multihost",
    "run_with_policy",
    "trainer_topology",
]
