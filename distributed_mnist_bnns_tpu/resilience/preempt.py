"""Preemption-aware graceful stop.

TPU fleet schedulers deliver SIGTERM and expect the job to vacate within
a grace window; the reference's answer was "lose the epoch and restart
by hand". Here a :class:`StopRequest` turns the signal into a flag the
training loop polls at every step boundary: the trainer finishes the
in-flight dispatch, writes a *mid-epoch* checkpoint (step, data
position and rng state in the meta — utils/checkpoint.py), then raises
:class:`Preempted`, which the CLI maps to :data:`PREEMPT_EXIT_CODE` and
``run_with_policy`` treats as "resume, don't count against the failure
budget".

Stdlib-only on purpose: importable before jax initializes a backend.
"""

from __future__ import annotations

import contextlib
import logging
import signal
import threading
from typing import Iterator, Optional, Tuple

log = logging.getLogger(__name__)

# EX_TEMPFAIL: "temporary failure, retrying later will succeed" — the
# distinct exit code a supervisor (or run_with_policy across processes)
# reads as "resume me", as opposed to 1 (crash) or 0 (done).
PREEMPT_EXIT_CODE = 75


class Preempted(RuntimeError):
    """Training stopped gracefully at a step boundary after a
    preemption request; state (if a checkpoint dir is configured) is on
    disk and the run is resumable with ``TrainConfig.resume``."""

    def __init__(self, epoch: int, step: int, reason: str = ""):
        super().__init__(
            f"preempted at epoch {epoch} step {step}"
            + (f" ({reason})" if reason else "")
        )
        self.epoch = epoch
        self.step = step
        self.reason = reason
        self.exit_code = PREEMPT_EXIT_CODE


class StopRequest:
    """Thread-safe "stop at the next step boundary" flag.

    ``request()`` can be called from a signal handler, another thread
    (a watchdog), or the chaos harness (``preempt`` fault) — the
    training loop only ever *polls* ``requested``, so the handler does
    no unsafe work. A second SIGINT while a stop is already pending
    escalates to ``KeyboardInterrupt`` (the usual "hit Ctrl-C twice to
    really die" contract)."""

    def __init__(self) -> None:
        self._event = threading.Event()
        self.reason: Optional[str] = None

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def request(self, reason: str = "stop requested") -> None:
        if not self._event.is_set():
            self.reason = reason
            log.warning(
                "graceful stop requested (%s): stopping at the next step "
                "boundary", reason,
            )
        self._event.set()

    def clear(self) -> None:
        self._event.clear()
        self.reason = None

    def _handler(self, signum, frame) -> None:
        name = signal.Signals(signum).name
        if self._event.is_set() and signum == signal.SIGINT:
            raise KeyboardInterrupt
        self.request(f"signal {name}")

    @contextlib.contextmanager
    def install(
        self, signals: Tuple[int, ...] = (signal.SIGTERM, signal.SIGINT)
    ) -> Iterator["StopRequest"]:
        """Install the graceful-stop handler for ``signals``, restoring
        the previous handlers on exit. Outside the main thread (where
        CPython forbids ``signal.signal``) this is a no-op: the flag
        still works via ``request()``."""
        previous = []
        try:
            for sig in signals:
                previous.append((sig, signal.signal(sig, self._handler)))
        except ValueError as e:  # not the main thread
            log.debug(
                "signal handlers not installed (%s); graceful stop "
                "remains reachable via StopRequest.request()", e,
            )
        try:
            yield self
        finally:
            for sig, old in previous:
                signal.signal(sig, old)
