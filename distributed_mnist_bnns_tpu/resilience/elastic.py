"""Elastic data-parallel membership: chaos-driven mesh shrink/grow.

On a preemptible TPU fleet, losing a worker mid-run is the COMMON case
— and until this module, it cost the whole job: the mesh is sized at
launch, so the only recovery was a full restart from a hand-carried
checkpoint. :func:`run_elastic` is an in-process supervisor that turns
a membership change into a *remesh* instead:

  1. **detect** — the seed-deterministic chaos kinds ``worker_lost`` /
     ``worker_restore`` (resilience/chaos) report membership changes to
     the supervisor's hook at a step boundary; the hook records the
     target world in a :class:`MembershipView` and requests a graceful
     stop exactly like a SIGTERM would (a ``membership_change`` event
     lands in the run log first);
  2. **checkpoint-or-roll-back** — the trainer's graceful-stop path
     writes a step-granular checkpoint at the detection boundary; the
     rebuild then restores the newest *digest-verified* generation
     (utils/checkpoint.load_checkpoint_resilient), rolling back past
     any corrupt one — so a save damaged in the same incident costs at
     most one generation, never the job;
  3. **remesh** — the trainer is rebuilt IN-PROCESS at the new world
     (``make_trainer(new_world)``: a smaller — or re-grown — mesh via
     ``parallel.mesh.make_mesh``), and the restore re-places every
     ``(world, ...)``-shaped row of the 1-bit compression state onto
     the new topology (parallel/remesh: worker EF rows fold by
     groupwise mean, segment-owner rows — including the ZeRO-sharded
     base-optimizer moments — re-cut position-preservingly). A
     ``remesh`` event + ``remesh_total{direction}`` counter and the
     ``world_size`` gauge record the transition; NO ``restart`` event
     is emitted — membership churn is routine, not failure, and does
     not consume the retry budget (the same reasoning that exempts
     preemption in :mod:`.policy`).

Non-membership failures keep :func:`.policy.run_with_policy` semantics
(same classification, backoff and budget — this loop is that one plus a
membership branch): transient errors rebuild at the CURRENT world after
a jittered backoff, fatal errors re-raise at once, plain preemptions
resume without burning the failure budget. One deliberate difference: a
graceful stop caused by a REAL process signal (``Preempted.reason``
starting with ``"signal "``) re-raises instead of resuming — a
scheduler's SIGTERM means this process must vacate the machine, and an
in-process supervisor that "resumed" it would fight its scheduler (the
CLI maps the re-raise to exit 75 so the external relaunch-with-resume
contract still holds).

Single-controller by design: this codebase's meshes live in one
process (the simulated 8-device CPU mesh, a single-host TPU slice), so
membership is a host-local decision. A multi-host deployment would put
this loop on the coordinator and broadcast the view — the state
re-placement half (parallel/remesh) is already topology-agnostic.

See RESILIENCE.md "Elastic membership"; proven end-to-end by
tests/test_elastic.py and the CI ``elastic-smoke`` job
(scripts/elastic_smoke.py).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, TypeVar

from .policy import (
    RetryPolicy,
    TrainingFailure,
    handle_failure,
    handle_preemption,
    trainer_topology,
)
from .preempt import Preempted

log = logging.getLogger(__name__)

T = TypeVar("T")

REMESH_TOTAL = "remesh_total"
WORLD_SIZE_GAUGE = "world_size"


@dataclass
class MembershipView:
    """The supervisor's view of data-parallel membership.

    ``full_world`` is the launch world — ``worker_restore`` without an
    explicit ``world=`` returns to it. ``world`` is the world the
    current trainer runs at; ``pending`` is a requested-but-not-yet-
    applied change (set by the chaos hook at the step boundary that
    detected it, consumed by the supervisor when the graceful stop
    surfaces as :class:`Preempted`)."""

    full_world: int
    world: int
    pending: Optional[Dict[str, Any]] = None


def _registry(telemetry: Any):
    if telemetry is not None:
        return telemetry.registry
    from ..obs import default_registry  # lazy: keep import-time light

    return default_registry()


def _wire_membership(trainer: Any, view: MembershipView) -> None:
    """Attach the membership hook to this trainer's chaos controller:
    record the target world on ``view``, bank a ``membership_change``
    event, and request a graceful stop at the same step boundary — the
    identical stop/checkpoint path a preemption takes, so the remesh
    resumes step-granularly from the detection point."""

    def on_membership(event, world=None, step=None, epoch=None):
        target = int(world) if world else view.full_world
        if target == view.world:
            log.info(
                "membership %s at step %s: already at world %d; "
                "no remesh needed", event, step, target,
            )
            return
        view.pending = {"event": event, "world": target, "step": step}
        trainer.telemetry.emit(
            "membership_change", event=event, world_from=view.world,
            world_to=target, step=step, epoch=epoch,
        )
        trainer.stop.request(
            f"membership change: worker {event} -> world {target}"
        )

    trainer.chaos.on_membership = on_membership


def run_elastic(
    make_trainer: Callable[[Optional[int]], Any],
    run: Callable[[Any], T],
    *,
    policy: Optional[RetryPolicy] = None,
    telemetry: Any = None,
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """Execute ``run(make_trainer(None))`` under elastic membership.

    ``make_trainer(world)`` builds a trainer: ``None`` means the
    configured launch world (honoring the caller's ``resume`` setting);
    an int means "rebuild at exactly that data-parallel world, resuming
    from the checkpoint directory" (the factory must force
    ``resume=True`` and set ``data_parallel=world``; the CLI's
    ``--elastic`` path and tests/test_elastic.py are the reference
    implementations). The restore re-places any world-shaped
    compression state automatically (``TrainConfig.elastic`` must be
    set — the trainer's resume path keys its remesh tolerance on it).

    ``telemetry``: an optional obs Telemetry sharing the run's event
    dir; ``remesh``/``restart`` events and the ``remesh_total`` /
    ``world_size`` instruments land there (falling back to the current
    trainer's telemetry / the process default registry).
    """
    policy = policy if policy is not None else RetryPolicy()
    failures = 0
    preemptions = 0
    view: Optional[MembershipView] = None
    world: Optional[int] = None
    remesh_event: Optional[Dict[str, Any]] = None
    remesh_t0: Optional[float] = None   # remesh-window span start
    while True:
        trainer = make_trainer(world)
        w, _ = trainer_topology(trainer)
        if view is None:
            view = MembershipView(full_world=w, world=w)
        elif w != view.world:
            raise TrainingFailure(
                f"make_trainer({view.world}) built a world-{w} trainer "
                "— the elastic factory must honor the requested world"
            )
        _wire_membership(trainer, view)
        _registry(telemetry).gauge(
            WORLD_SIZE_GAUGE,
            "current data-parallel world size (elastic membership)",
        ).set(view.world)
        if remesh_event is not None:
            # Emitted AFTER the rebuild: the previous trainer sealed
            # its telemetry before Preempted propagated (emit-after-
            # close is a silent no-op), so without a supervisor
            # telemetry the event must ride the new trainer's log.
            tel = (
                telemetry if telemetry is not None
                else getattr(trainer, "telemetry", None)
            )
            if tel is not None:
                tel.emit("remesh", **remesh_event)
                tr = getattr(tel, "tracer", None)
                if tr is not None and tr.enabled \
                        and remesh_t0 is not None:
                    # The remesh WINDOW — membership stop observed ->
                    # rebuilt trainer ready — as one span, so elastic
                    # churn shows up in `cli trace` next to the step
                    # spans it displaced.
                    tr.record(
                        "train.remesh", kind="remesh", t0=remesh_t0,
                        t1=time.monotonic(), **remesh_event,
                    )
            remesh_event = None
            remesh_t0 = None
        def consume_pending():
            """Apply the observed membership change to the NEXT
            rebuild: remesh bookkeeping (counter + stashed event) and
            the new target world."""
            nonlocal remesh_event, remesh_t0, world
            remesh_t0 = time.monotonic()
            pend, view.pending = view.pending, None
            old_world, new_world = view.world, int(pend["world"])
            direction = "shrink" if new_world < old_world else "grow"
            _registry(telemetry).counter(
                REMESH_TOTAL,
                "elastic mesh rebuilds (label: direction=shrink|grow)",
            ).inc(direction=direction)
            remesh_event = dict(
                direction=direction, world_from=old_world,
                world_to=new_world, event=pend["event"],
                step=pend.get("step"),
            )
            log.warning(
                "remesh (%s): world %d -> %d — rebuilding the mesh "
                "and re-placing state from the newest verified "
                "checkpoint generation (no job restart)",
                direction, old_world, new_world,
            )
            view.world = new_world
            world = new_world

        try:
            return run(trainer)
        except Preempted as e:
            if (e.reason or "").startswith("signal "):
                # A REAL scheduler signal: the whole process must
                # vacate; resuming in-process — even with a membership
                # change pending — would fight the scheduler. Checked
                # BEFORE the pending branch so a SIGTERM that raced a
                # worker_lost to the stop flag still wins. Hand the
                # resumable exit up (cli -> 75).
                raise
            if view.pending is not None:
                consume_pending()
                continue  # membership churn never burns the budget
            preemptions = handle_preemption(
                e, policy=policy, preemptions=preemptions,
                telemetry=telemetry, trainer=trainer,
            )
            world = view.world
        except BaseException as e:
            failures = handle_failure(
                e, policy=policy, failures=failures,
                telemetry=telemetry, trainer=trainer, sleep=sleep,
                context=f" at world {view.world}",
            )
            if view.pending is not None:
                # A transient fault raced the membership graceful stop
                # to the step boundary (e.g. worker_lost and step_fault
                # scripted at the same step): the fired membership rule
                # is exhausted in the chaos ledger and will never
                # re-request the stop, so the observed change must be
                # applied HERE or it is silently dropped (and a later
                # unrelated Preempted would be misread as a remesh).
                # The failure above still consumed its retry budget.
                consume_pending()
            else:
                world = view.world
