"""Fault-injection harness: named, seed-deterministic chaos.

Recovery code that is never exercised is recovery code that does not
work. This module scripts *exact* failure sequences against a live run
so tests and CI can assert the resilient path end to end — the same
find-then-fence idea as the analysis/ linter+sanitizers, applied to
process/IO/state faults.

Spec grammar (``TrainConfig.chaos`` / ``--chaos`` / ``JG_CHAOS`` env)::

    spec     := entry (";" entry)*
    entry    := kind ["@" arg ("," arg)*]
    arg      := key "=" value
    kind     := step_fault | data_io | preempt | slow_host
              | ckpt_corrupt | ckpt_truncate
              | infer_slow | infer_error
              | worker_lost | worker_restore
              | host_lost | host_restore
    key      := step | epoch | p | times | delay_s | world | hosts

``step``/``epoch`` trigger a rule the first time the run reaches that
global optimizer step / epoch (``>=`` semantics, so scan-chunked
dispatches that jump several steps at once still fire). ``p`` is a
per-opportunity probability drawn from a rule-local RNG seeded with
``(run seed, rule key)`` — deterministic replay for a fixed seed and
call sequence. ``times`` caps total fires (default 1; ``-1`` =
unlimited); ``delay_s`` is the slow-host stall length.

Fault points:

  step_fault     transient exception before a train-step dispatch
                 (:class:`ChaosStepFault`, classified retryable)
  data_io        batch-IO error at the same point
                 (:class:`ChaosIOError`)
  preempt        simulated scheduler preemption: requests a graceful
                 stop exactly as a SIGTERM would (trainer wires
                 ``on_preempt`` to its StopRequest; without a callback
                 a real SIGTERM is sent to this process)
  slow_host      stalls the host ``delay_s`` seconds (straggler sim)
  ckpt_corrupt   flips bytes in the just-written checkpoint artifact
  ckpt_truncate  truncates it to half its length
  infer_slow     stalls the serving predictor call ``delay_s`` seconds
                 (backend-stall sim; the serve/ engine counts a call
                 past its stall budget as a breaker failure)
  infer_error    raises :class:`ChaosInferError` at the predictor call
                 (transient backend error)
  worker_lost    simulated loss of data-parallel workers: reports a
                 membership change to the elastic supervisor
                 (``world=N`` — the post-loss world size — is
                 mandatory), which shrinks the mesh and re-places state
                 from the newest verified checkpoint generation
                 (resilience/elastic, RESILIENCE.md "Elastic
                 membership"). Requires the elastic loop; a trainer
                 without ``elastic=True`` rejects the spec at init.
  worker_restore the lost workers came back: membership change back to
                 ``world=N`` (default: the launch world) — the
                 supervisor regrows the mesh and re-splits state
  host_lost      REAL host loss in the multi-host elastic runtime
                 (``hosts=N`` — the post-loss host count — is
                 mandatory): every rank process whose rank is >= N
                 SIGKILLs itself at the step boundary; the survivors
                 detect the dead world through the host collective
                 (parallel/hostcomm EOF/timeout), vacate via the
                 preempt path WITHOUT saving, and the multihost
                 supervisor (resilience/multihost) relaunches the
                 world at the shrunken count. Requires the multihost
                 runtime; a trainer without a host channel rejects the
                 spec at init.
  host_restore   the lost hosts came back: requests a regrow to
                 ``hosts=N`` (default: the launch count) — every rank
                 saves and vacates gracefully (exit 75) and the
                 supervisor relaunches at the restored count

Serving rules trigger on ``step`` = the serving engine's micro-batch
sequence number (or ``p``), so one spec composes training and serving
chaos; ``epoch`` has no serving meaning and never fires there.

Fire counts live in a **process-global ledger** keyed by spec entry, so
a ``times=1`` fault does not re-fire when the retry loop rebuilds the
Trainer (which re-parses the same spec) and replays the same step.
Tests isolate themselves with :func:`reset_fire_counts`.

Every fire increments ``faults_injected_total`` (label ``kind``) and,
with a telemetry sink attached, emits a ``fault_injected`` event before
the fault takes effect — the post-mortem trail proves which failures
were scripted.

With tracing armed (obs/trace, OBSERVABILITY.md "Tracing") every fire
is ALSO a span: an instant ``chaos.<kind>`` marker at the fault point,
parented to whatever span is current on the firing thread (the serving
batch / LM decode iteration / nothing for the trainer's step boundary)
— and the stall kinds (``slow_host``/``infer_slow``) additionally wrap
their sleep in a duration ``chaos.stall`` span, so fault→latency
causality is a tree link in the trace, not a timestamp correlation
exercise over two log greps.
"""

from __future__ import annotations

import logging
import os
import random
import signal
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

log = logging.getLogger(__name__)

ENV_SPEC = "JG_CHAOS"

FAULT_KINDS = frozenset({
    "step_fault", "data_io", "preempt", "slow_host",
    "ckpt_corrupt", "ckpt_truncate",
    "infer_slow", "infer_error",
    "worker_lost", "worker_restore",
    "host_lost", "host_restore",
})

# Which kinds each fault point dispatches — a rule only evaluates its
# trigger (and, for p=, draws its RNG) at its own point, so a mixed
# training+serving spec keeps per-rule probabilistic replay
# deterministic at every point.
_STEP_KINDS = frozenset({"step_fault", "data_io", "preempt", "slow_host"})
_CKPT_KINDS = frozenset({"ckpt_corrupt", "ckpt_truncate"})
_INFER_KINDS = frozenset({"infer_slow", "infer_error"})
# Membership kinds fire at the trainer step boundary like _STEP_KINDS
# but are dispatched to the elastic supervisor's hook, not the trainer —
# exported so the Trainer can reject them loudly without --elastic.
MEMBERSHIP_KINDS = frozenset({"worker_lost", "worker_restore"})
# Host-level membership kinds (the multi-host elastic runtime): fire at
# the trainer step boundary and dispatch to the multihost hook — which
# may SIGKILL THIS PROCESS (host_lost on a doomed rank). Exported so the
# Trainer can reject them loudly outside the multihost runtime.
HOST_KINDS = frozenset({"host_lost", "host_restore"})

FAULTS_TOTAL = "faults_injected_total"

# Process-global fire ledger (see module docstring): rule key -> fires.
_FIRE_LEDGER: Dict[str, int] = {}


def reset_fire_counts() -> None:
    """Forget all fires — call between independent chaos scenarios."""
    _FIRE_LEDGER.clear()


class ChaosFault(RuntimeError):
    """Base marker for injected faults (classified transient by
    resilience.policy)."""


class ChaosStepFault(ChaosFault):
    """Injected transient train-step exception."""


class ChaosIOError(ChaosFault, OSError):
    """Injected data-batch IO error."""


class ChaosInferError(ChaosFault):
    """Injected transient serving-backend error (predictor call)."""


@dataclass
class FaultRule:
    """One parsed spec entry. ``key`` identifies the entry in the
    process-global fire ledger (spec text + position)."""

    kind: str
    step: Optional[int] = None
    epoch: Optional[int] = None
    p: float = 0.0
    times: int = 1
    delay_s: float = 1.0
    world: Optional[int] = None  # membership kinds: post-change world
    hosts: Optional[int] = None  # host kinds: post-change host count
    key: str = ""


def parse_chaos_spec(spec: str) -> List[FaultRule]:
    """Parse the chaos spec grammar (module docstring); raises
    ``ValueError`` with the offending entry on any malformed input so a
    typo'd CI spec fails loudly, not silently-no-chaos."""
    rules: List[FaultRule] = []
    for i, raw in enumerate(e.strip() for e in spec.split(";")):
        if not raw:
            continue
        kind, _, argstr = raw.partition("@")
        kind = kind.strip()
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown chaos fault kind {kind!r} in {raw!r} "
                f"(have: {', '.join(sorted(FAULT_KINDS))})"
            )
        rule = FaultRule(kind=kind, key=f"{raw}#{i}")
        casts = {"step": int, "epoch": int, "p": float, "times": int,
                 "delay_s": float, "world": int, "hosts": int}
        for arg in (a.strip() for a in argstr.split(",")):
            if not arg:
                continue
            k, sep, v = arg.partition("=")
            if not sep:
                raise ValueError(f"chaos arg {arg!r} in {raw!r} is not k=v")
            if k not in casts:
                raise ValueError(
                    f"unknown chaos key {k!r} in {raw!r} "
                    "(have: step, epoch, p, times, delay_s, world, hosts)"
                )
            try:
                setattr(rule, k, casts[k](v))
            except ValueError as e:
                raise ValueError(
                    f"bad chaos value {v!r} for {k!r} in {raw!r}"
                ) from e
        if rule.step is None and rule.epoch is None and rule.p <= 0:
            raise ValueError(
                f"chaos entry {raw!r} needs a trigger: step=, epoch= or p="
            )
        if rule.world is not None and kind not in MEMBERSHIP_KINDS:
            raise ValueError(
                f"chaos key 'world' in {raw!r} only applies to "
                "worker_lost/worker_restore"
            )
        if kind == "worker_lost" and (rule.world is None or rule.world < 1):
            raise ValueError(
                f"chaos entry {raw!r} needs world=N >= 1 (the post-loss "
                "data-parallel world size)"
            )
        if rule.world is not None and rule.world < 1:
            raise ValueError(
                f"chaos entry {raw!r}: world must be >= 1, "
                f"got {rule.world}"
            )
        if rule.hosts is not None and kind not in HOST_KINDS:
            raise ValueError(
                f"chaos key 'hosts' in {raw!r} only applies to "
                "host_lost/host_restore"
            )
        if kind == "host_lost" and (rule.hosts is None or rule.hosts < 1):
            raise ValueError(
                f"chaos entry {raw!r} needs hosts=N >= 1 (the post-loss "
                "host count)"
            )
        if rule.hosts is not None and rule.hosts < 1:
            raise ValueError(
                f"chaos entry {raw!r}: hosts must be >= 1, "
                f"got {rule.hosts}"
            )
        rules.append(rule)
    return rules


class ChaosController:
    """Evaluates the parsed rules at the instrumented fault points.

    Hooks (all cheap no-ops when ``active`` is False):
      * ``on_step(step=, epoch=)`` — called by the trainer before each
        dispatch; stalls (slow_host), raises (data_io/step_fault) or
        requests preemption (preempt), in spec order.
      * ``on_checkpoint_written(path, step=, epoch=)`` — called by the
        checkpoint writers after the artifact lands; corrupts or
        truncates it in place (a directory artifact has its largest
        file hit).
    """

    def __init__(
        self,
        rules: List[FaultRule],
        *,
        seed: int = 0,
        telemetry: Any = None,
        spec: str = "",
    ):
        self.rules = rules
        self.seed = seed
        self.telemetry = telemetry
        self.spec = spec
        # Wired by the trainer to StopRequest.request; the fallback
        # exercises the real signal path.
        self.on_preempt: Optional[Callable[[str], None]] = None
        # Wired by the elastic supervisor (resilience/elastic): called
        # as on_membership(event, world=, step=, epoch=) with event
        # "lost"|"restored" when a membership kind fires. Without a
        # supervisor a fired membership rule raises — silently dropping
        # a scripted worker loss would make the chaos test vacuous.
        self.on_membership: Optional[Callable[..., None]] = None
        # Wired by the multihost trainer: called as
        # on_host_membership(event, hosts=, step=, epoch=) with event
        # "lost"|"restored". The "lost" handler SIGKILLs the process
        # when its own rank is doomed — control may never return.
        self.on_host_membership: Optional[Callable[..., None]] = None
        self._rngs = {
            r.key: random.Random(f"{seed}:{r.key}") for r in rules
        }

    @classmethod
    def from_config(
        cls, spec: Optional[str], *, seed: int = 0, telemetry: Any = None
    ) -> "ChaosController":
        """Build from an explicit spec, falling back to the ``JG_CHAOS``
        env var when ``spec`` is None (how CI arms chaos without
        touching call sites); empty/unset -> inactive controller."""
        if spec is None:
            spec = os.environ.get(ENV_SPEC, "")
        rules = parse_chaos_spec(spec) if spec else []
        return cls(rules, seed=seed, telemetry=telemetry, spec=spec or "")

    @property
    def active(self) -> bool:
        return bool(self.rules)

    def _span_tracer(self):
        """The telemetry sink's tracer when tracing is armed, else
        None — chaos must stay importable/usable with a bare telemetry
        stub (tests pass all kinds of fakes)."""
        tr = getattr(self.telemetry, "tracer", None)
        return tr if tr is not None and getattr(tr, "enabled", False) \
            else None

    def _stall(self, rule: FaultRule, point: str,
               step: Optional[int]) -> None:
        """The scripted sleep, wrapped in a duration ``stall`` span so
        the stalled window itself is visible in the trace (and in tail
        attribution) — not just the instant fire marker."""
        tr = self._span_tracer()
        if tr is None:
            time.sleep(rule.delay_s)
            return
        with tr.start(
            "chaos.stall", kind="stall", fault=rule.kind, point=point,
            step=step, delay_s=rule.delay_s,
        ):
            time.sleep(rule.delay_s)

    # -- trigger evaluation --------------------------------------------------

    def _should_fire(
        self, rule: FaultRule, step: Optional[int], epoch: Optional[int]
    ) -> bool:
        if 0 <= rule.times <= _FIRE_LEDGER.get(rule.key, 0):
            return False
        if rule.step is not None:
            return step is not None and step >= rule.step
        if rule.epoch is not None:
            return epoch is not None and epoch >= rule.epoch
        return self._rngs[rule.key].random() < rule.p

    def _record(
        self, rule: FaultRule, point: str,
        step: Optional[int], epoch: Optional[int], detail: str = "",
    ) -> None:
        _FIRE_LEDGER[rule.key] = _FIRE_LEDGER.get(rule.key, 0) + 1
        from ..obs import default_registry  # lazy: keep import-time light

        registry = (
            self.telemetry.registry if self.telemetry is not None
            else default_registry()
        )
        registry.counter(
            FAULTS_TOTAL, "chaos faults fired by kind"
        ).inc(kind=rule.kind)
        if self.telemetry is not None:
            # "fault" not "kind": the envelope already owns the kind
            # field (= "fault_injected").
            self.telemetry.emit(
                "fault_injected", fault=rule.kind, point=point,
                step=step, epoch=epoch, detail=detail, rule=rule.key,
            )
        tr = self._span_tracer()
        if tr is not None:
            # Instant marker span at the fault point; parenting to the
            # firing thread's current span (serve batch / LM decode
            # iteration) makes fault->latency causality first-class.
            now = time.monotonic()
            tr.record(
                f"chaos.{rule.kind}", kind="chaos", t0=now, t1=now,
                fault=rule.kind, point=point, step=step, epoch=epoch,
                **({"detail": detail} if detail else {}),
            )
        log.warning(
            "chaos: injected %s at step=%s epoch=%s%s",
            rule.kind, step, epoch, f" ({detail})" if detail else "",
        )

    def mark_reached(
        self, *, step: Optional[int] = None, epoch: Optional[int] = None
    ) -> None:
        """Resume bookkeeping across PROCESS restarts: the in-memory
        fire ledger dies with the process, but a run that restored to
        ``step``/``epoch`` only got there because the faults scripted at
        or before that position already fired in the previous process.
        Counting them as fired here keeps the exit-75 ``--resume``
        contract live — without it, ``preempt@step=K`` would refire on
        the first post-restore step (``>=`` semantics) and the job could
        never progress past K. Called by the trainer after a successful
        restore. Step rules at ``<= step`` are exhausted up to their
        ``times`` cap. Epoch rules depend on the fault point: step-
        boundary kinds (step_fault/data_io/preempt/slow_host, and the
        membership kinds worker_lost/worker_restore, which fire at the
        same point) fire at
        the START of their epoch, so being resumed AT epoch E means an
        epoch-``<= E`` rule fired (``preempt@epoch=E`` produced this
        very resume — it must not refire and relaunch-loop); checkpoint-
        write kinds fire at the END of their epoch, whose save has only
        happened for epochs strictly before the resumed one."""
        for rule in self.rules:
            fired = _FIRE_LEDGER.get(rule.key, 0)
            if rule.times < 0 or fired >= rule.times:
                continue
            if rule.kind in _INFER_KINDS:
                # serving rules count micro-batches, not optimizer
                # steps — a training resume says nothing about them.
                continue
            at_save = rule.kind in ("ckpt_corrupt", "ckpt_truncate")
            hit = (
                rule.step is not None
                and step is not None
                and rule.step <= step
            ) or (
                rule.step is None
                and rule.epoch is not None
                and epoch is not None
                and (rule.epoch < epoch if at_save else rule.epoch <= epoch)
            )
            if hit:
                _FIRE_LEDGER[rule.key] = rule.times
                log.info(
                    "chaos: rule %s counted as already fired before the "
                    "restored position (step=%s epoch=%s)",
                    rule.key, step, epoch,
                )

    # -- fault points --------------------------------------------------------

    def on_step(
        self, *, step: Optional[int] = None, epoch: Optional[int] = None
    ) -> None:
        """Pre-dispatch fault point (raises for data_io/step_fault)."""
        for rule in self.rules:
            if (
                rule.kind not in _STEP_KINDS
                and rule.kind not in MEMBERSHIP_KINDS
                and rule.kind not in HOST_KINDS
            ):
                continue
            if not self._should_fire(rule, step, epoch):
                continue
            if rule.kind in HOST_KINDS:
                if self.on_host_membership is None:
                    raise ValueError(
                        f"chaos {rule.kind} fired with no multihost "
                        "runtime attached — host faults need the "
                        "multihost elastic loop (resilience.multihost."
                        "run_elastic_multihost with JG_MH_* ranks)"
                    )
                self._record(
                    rule, "step", step, epoch,
                    f"hosts={rule.hosts}" if rule.hosts is not None
                    else "hosts=launch",
                )
                # May SIGKILL this process (host_lost on a doomed rank).
                self.on_host_membership(
                    "lost" if rule.kind == "host_lost" else "restored",
                    hosts=rule.hosts, step=step, epoch=epoch,
                )
            elif rule.kind in MEMBERSHIP_KINDS:
                if self.on_membership is None:
                    raise ValueError(
                        f"chaos {rule.kind} fired with no elastic "
                        "supervisor attached — membership faults need "
                        "the elastic training loop (cli train --elastic "
                        "/ resilience.elastic.run_elastic)"
                    )
                self._record(
                    rule, "step", step, epoch,
                    f"world={rule.world}" if rule.world is not None
                    else "world=launch",
                )
                self.on_membership(
                    "lost" if rule.kind == "worker_lost" else "restored",
                    world=rule.world, step=step, epoch=epoch,
                )
            elif rule.kind == "slow_host":
                self._record(
                    rule, "step", step, epoch, f"stall {rule.delay_s}s"
                )
                self._stall(rule, "step", step)
            elif rule.kind == "data_io":
                self._record(rule, "step", step, epoch)
                raise ChaosIOError(
                    f"chaos: injected batch-IO failure at step {step}"
                )
            elif rule.kind == "step_fault":
                self._record(rule, "step", step, epoch)
                raise ChaosStepFault(
                    f"chaos: injected transient step fault at step {step}"
                )
            elif rule.kind == "preempt":
                self._record(rule, "step", step, epoch)
                if self.on_preempt is not None:
                    self.on_preempt(f"chaos preempt at step {step}")
                else:
                    os.kill(os.getpid(), signal.SIGTERM)

    def on_infer(self, *, step: Optional[int] = None) -> None:
        """Serving predictor-call fault point (serve/ engine): stalls
        the call (``infer_slow``) or raises :class:`ChaosInferError`
        (``infer_error``). ``step`` is the engine's micro-batch
        sequence number — the serving analogue of the optimizer step,
        so the ``@step=`` trigger grammar carries over unchanged."""
        for rule in self.rules:
            if rule.kind not in _INFER_KINDS:
                continue
            if not self._should_fire(rule, step, None):
                continue
            if rule.kind == "infer_slow":
                self._record(
                    rule, "infer", step, None, f"stall {rule.delay_s}s"
                )
                self._stall(rule, "infer", step)
            else:
                self._record(rule, "infer", step, None)
                raise ChaosInferError(
                    f"chaos: injected backend error at serve batch {step}"
                )

    def on_checkpoint_written(
        self, path: str, *,
        step: Optional[int] = None, epoch: Optional[int] = None,
    ) -> None:
        """Post-write fault point: damage the artifact in place. For a
        hardlinked latest/generation pair the in-place edit hits both —
        exactly the "this save's bytes were bad" scenario the
        generation rollback exists for."""
        for rule in self.rules:
            if rule.kind not in _CKPT_KINDS:
                continue
            if not self._should_fire(rule, step, epoch):
                continue
            victim = path
            if os.path.isdir(path):
                files = [
                    os.path.join(root, f)
                    for root, _, names in os.walk(path) for f in names
                ]
                if not files:
                    continue
                victim = max(files, key=os.path.getsize)
            size = os.path.getsize(victim)
            if rule.kind == "ckpt_truncate":
                os.truncate(victim, size // 2)
                detail = f"{victim}: {size} -> {size // 2} bytes"
            else:
                with open(victim, "r+b") as f:
                    f.seek(size // 2)
                    chunk = f.read(64) or b"\x00"
                    f.seek(size // 2)
                    f.write(bytes(b ^ 0xFF for b in chunk))
                detail = f"{victim}: flipped {min(64, size)} bytes"
            self._record(rule, "checkpoint_write", step, epoch, detail)
