"""Multi-host elastic supervisor: real processes, real host loss.

:mod:`.elastic` rebuilds an in-process mesh when a chaos rule reports a
membership change. This module is the same contract one level up, where
"worker" means an OS PROCESS: :func:`run_elastic_multihost` launches one
subprocess per host rank (each its own single-process jax runtime,
exchanging gradients over the parallel/hostcomm TCP collective), watches
their exits, and turns a SIGKILLed rank into a *relaunch at the
surviving host count* instead of a dead job:

  1. **detect** — a killed rank exits with a signal status; its
     survivors notice the dead socket inside one step, vacate via the
     preempt path WITHOUT saving (the step that consumed the zeroed
     exchange is garbage), and exit :data:`~.preempt.PREEMPT_EXIT_CODE`;
  2. **membership, not failure** — any signal-killed rank in a
     generation is classified as a host loss: the world shrinks to the
     survivors, the retry budget is NOT consumed (the same exemption
     membership churn gets in :mod:`.elastic`), and the transition is
     recorded in ``membership.json`` next to the checkpoint generations
     on the shared store — the host-level
     :class:`~.elastic.MembershipView`;
  3. **relaunch** — a fresh generation of rank processes starts at the
     new host count (fresh ranks 0..n-1, fresh conductor port, resume
     from the newest digest-verified checkpoint generation); the
     trainer's elastic restore re-folds the per-host compression rows
     (parallel/remesh), so the post-shrink trajectory is bitwise what a
     fresh resume at that world would produce.

Regrow rides the same loop: the chaos ``host_restore`` rule makes every
rank stop gracefully (checkpoint saved) after rank 0 drops a
``restore_request.json`` in the store; the supervisor consumes it and
relaunches at the requested (default: full) host count.

Exit-code classification per generation, in precedence order:

  =====================  ==================================================
  every rank 0           training complete -> return
  any rank signal-killed  host loss -> shrink to survivors (budget-free)
  restore_request.json    regrow to the requested hosts (budget-free)
  any rank exited 75      plain preemption -> resume, preemption budget
  anything else           transient failure -> backoff, restart budget
  =====================  ==================================================

See RESILIENCE.md "Multi-host elastic membership"; driven end-to-end by
scripts/multihost_smoke.py (CI ``multihost-smoke``) and
tests/test_multihost.py.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import subprocess
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from .policy import RetryPolicy, TrainingFailure
from .preempt import PREEMPT_EXIT_CODE

log = logging.getLogger(__name__)

MEMBERSHIP_FILE = "membership.json"
RESTORE_REQUEST_FILE = "restore_request.json"
HOST_REMESH_TOTAL = "host_remesh_total"
HOST_WORLD_GAUGE = "host_world_size"

# Env contract between the supervisor and its rank processes. The
# single source of truth for the names is parallel/distributed
# (detect_multihost); they are duplicated here as literals so this
# module stays importable without pulling the whole parallel package
# (test_imports guards the pairing).
ENV_RANK = "JG_MH_RANK"
ENV_HOSTS = "JG_MH_HOSTS"
ENV_PORT = "JG_MH_PORT"
ENV_STORE = "JG_MH_STORE"


@dataclass
class HostMembershipView:
    """The supervisor's view of host-level membership, persisted to
    ``membership.json`` on the shared store after every transition so a
    restarted supervisor — or a post-incident reader — sees the world
    the checkpoint generations were written at.

    ``full_hosts`` is the launch world (``host_restore`` without an
    explicit count regrows to it); ``hosts`` is the current world;
    ``generation`` counts supervisor relaunches (every spawn, not just
    remeshes — forensics for "how many lives did this run use")."""

    full_hosts: int
    hosts: int
    generation: int = 0
    history: List[Dict[str, Any]] = field(default_factory=list)

    def record(self, store: str, **transition: Any) -> None:
        """Append a transition and atomically rewrite the view file."""
        if transition:
            self.history.append(
                {"generation": self.generation, **transition}
            )
        os.makedirs(store, exist_ok=True)
        path = os.path.join(store, MEMBERSHIP_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "full_hosts": self.full_hosts,
                    "hosts": self.hosts,
                    "generation": self.generation,
                    "history": self.history[-50:],
                },
                f, indent=2,
            )
        os.replace(tmp, path)  # atomic: readers never see a torn view


def read_membership(store: str) -> Optional[Dict[str, Any]]:
    """The persisted view, or None (missing/corrupt — a torn write is
    impossible by construction, but a foreign file is not)."""
    try:
        with open(os.path.join(store, MEMBERSHIP_FILE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port. Each generation gets a fresh
    conductor port: the previous conductor may have died holding the
    old one, and survivors' half-closed sockets can linger in
    TIME_WAIT."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def _signal_name(returncode: int) -> str:
    try:
        return signal.Signals(-returncode).name
    except ValueError:
        return f"signal {-returncode}"


def run_elastic_multihost(
    cmd: Sequence[str],
    *,
    hosts: int,
    store: str,
    policy: Optional[RetryPolicy] = None,
    env: Optional[Dict[str, str]] = None,
    events: Any = None,
    registry: Any = None,
    generation_timeout_s: Optional[float] = None,
    poll_s: float = 0.2,
    sleep=time.sleep,
) -> int:
    """Supervise ``cmd`` as an elastic ``hosts``-rank world.

    ``cmd`` is launched once per rank with the ``JG_MH_*`` env set
    (rank, world size, conductor port, shared ``store``); the command
    must run a resumable trainer (``--elastic --resume`` + a checkpoint
    dir on the shared store) so a relaunch continues instead of
    restarting. ``store`` also carries ``membership.json`` and the
    ``restore_request.json`` regrow handshake.

    ``events``: an optional obs EventLog/Telemetry-like with ``emit``;
    ``registry``: an optional obs MetricRegistry for the
    ``host_remesh_total`` counter and ``host_world_size`` gauge.
    ``generation_timeout_s`` bounds one generation's wall clock — a hung
    world is killed and classified transient.

    Returns 0 when every rank of a generation exits cleanly. Raises
    :class:`TrainingFailure` past the retry/preemption budget, or when
    the world shrinks below one host.
    """
    if hosts < 1:
        raise ValueError(f"hosts must be >= 1, got {hosts}")
    policy = policy if policy is not None else RetryPolicy()
    view = HostMembershipView(full_hosts=hosts, hosts=hosts)
    persisted = read_membership(store)
    if persisted and persisted.get("hosts"):
        # A supervisor restart mid-incident resumes at the persisted
        # world — the checkpoint generations were written at it.
        view.hosts = int(persisted["hosts"])
        view.generation = int(persisted.get("generation") or 0)
        view.history = list(persisted.get("history") or [])
    restarts = 0
    preemptions = 0

    def _emit(kind: str, **fields: Any) -> None:
        if events is not None:
            events.emit(kind, **fields)

    def _gauge() -> None:
        if registry is not None:
            registry.gauge(
                HOST_WORLD_GAUGE,
                "current multihost elastic world size (host count)",
            ).set(view.hosts)

    def _remesh_counter(direction: str) -> None:
        if registry is not None:
            registry.counter(
                HOST_REMESH_TOTAL,
                "multihost relaunches at a changed host count "
                "(label: direction=shrink|grow)",
            ).inc(direction=direction)

    view.record(store)
    while True:
        n = view.hosts
        port = _free_port()
        view.generation += 1
        view.record(store)
        _gauge()
        log.info(
            "launching multihost generation %d: %d host(s), "
            "conductor port %d", view.generation, n, port,
        )
        procs: List[subprocess.Popen] = []
        base_env = dict(os.environ)
        base_env.update(env or {})
        base_env[ENV_HOSTS] = str(n)
        base_env[ENV_PORT] = str(port)
        base_env[ENV_STORE] = store
        try:
            for rank in range(n):
                rank_env = dict(base_env)
                rank_env[ENV_RANK] = str(rank)
                procs.append(
                    subprocess.Popen(list(cmd), env=rank_env)
                )
        except OSError:
            for p in procs:
                p.kill()
            raise
        t0 = time.monotonic()
        timed_out = False
        while any(p.poll() is None for p in procs):
            if (
                generation_timeout_s is not None
                and time.monotonic() - t0 > generation_timeout_s
            ):
                timed_out = True
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for p in procs:
                    p.wait()
                break
            sleep(poll_s)
        rcs = [p.returncode for p in procs]
        log.info(
            "generation %d exited: %s", view.generation,
            {r: rc for r, rc in enumerate(rcs)},
        )

        if timed_out:
            # Supervisor-killed ranks are NOT a host loss — classify the
            # hang as a transient failure below (budget consumed).
            restarts += 1
            if restarts > policy.max_restarts:
                raise TrainingFailure(
                    f"multihost generation hung past "
                    f"{generation_timeout_s}s {restarts} times; giving up"
                )
            delay = policy.backoff(restarts)
            _emit(
                "host_membership", event="timeout", hosts=n,
                generation=view.generation, budget_used=restarts,
                backoff_s=round(delay, 3),
            )
            log.warning(
                "generation %d hung (> %ss); killed, restarting at "
                "world %d in %.2fs (%d/%d)", view.generation,
                generation_timeout_s, n, delay, restarts,
                policy.max_restarts,
            )
            sleep(delay)
            continue

        if all(rc == 0 for rc in rcs):
            _emit(
                "host_membership", event="complete", hosts=n,
                generation=view.generation,
            )
            view.record(store, event="complete", hosts=n)
            return 0

        killed = [r for r, rc in enumerate(rcs) if rc < 0]
        if killed:
            survivors = n - len(killed)
            if survivors < 1:
                raise TrainingFailure(
                    f"all {n} host(s) killed "
                    f"({[_signal_name(rcs[r]) for r in killed]}); "
                    "nothing left to shrink to"
                )
            _remesh_counter("shrink")
            _emit(
                "host_membership", event="lost", direction="shrink",
                hosts_from=n, hosts_to=survivors, killed_ranks=killed,
                signals=[_signal_name(rcs[r]) for r in killed],
                generation=view.generation, budget_used=0,
            )
            log.warning(
                "host loss: rank(s) %s killed (%s) — relaunching at "
                "%d surviving host(s) from the newest verified "
                "checkpoint generation (retry budget untouched)",
                killed, ", ".join(_signal_name(rcs[r]) for r in killed),
                survivors,
            )
            view.hosts = survivors
            view.record(
                store, event="lost", hosts_from=n, hosts_to=survivors,
                killed_ranks=killed,
            )
            continue  # membership churn never burns the budget

        req_path = os.path.join(store, RESTORE_REQUEST_FILE)
        if any(rc == PREEMPT_EXIT_CODE for rc in rcs) and os.path.exists(
            req_path
        ):
            try:
                with open(req_path) as f:
                    req = json.load(f)
            except (OSError, ValueError):
                req = {}
            try:
                os.remove(req_path)  # consumed: a one-shot handshake
            except OSError:
                pass
            target = int(req.get("hosts") or view.full_hosts)
            if target == view.hosts:
                log.info(
                    "restore request for world %d: already there; "
                    "resuming", target,
                )
            else:
                direction = "grow" if target > view.hosts else "shrink"
                _remesh_counter(direction)
                _emit(
                    "host_membership", event="restored",
                    direction=direction, hosts_from=view.hosts,
                    hosts_to=target, generation=view.generation,
                    budget_used=0,
                )
                log.warning(
                    "host restore: relaunching at %d host(s) "
                    "(was %d; retry budget untouched)", target, view.hosts,
                )
                view.record(
                    store, event="restored", hosts_from=view.hosts,
                    hosts_to=target,
                )
                view.hosts = target
            continue

        if any(rc == PREEMPT_EXIT_CODE for rc in rcs):
            # A plain graceful vacate (SIGTERM, chaos preempt): resume
            # at the same world, counted against the preemption budget
            # exactly like run_with_policy would.
            preemptions += 1
            if preemptions > policy.max_preemptions:
                raise TrainingFailure(
                    f"preempted {preemptions} times; giving up"
                )
            _emit(
                "host_membership", event="preempted", hosts=n,
                generation=view.generation, budget_used=preemptions,
            )
            log.warning(
                "world vacated (exit %d); resuming at %d host(s) "
                "(%d/%d preemptions)", PREEMPT_EXIT_CODE, n,
                preemptions, policy.max_preemptions,
            )
            continue

        bad = {r: rc for r, rc in enumerate(rcs) if rc != 0}
        restarts += 1
        if restarts > policy.max_restarts:
            raise TrainingFailure(
                f"multihost training failed {restarts} times "
                f"(last exits: {bad}); giving up"
            )
        delay = policy.backoff(restarts)
        _emit(
            "host_membership", event="failed", hosts=n, exits=bad,
            generation=view.generation, budget_used=restarts,
            backoff_s=round(delay, 3),
        )
        log.warning(
            "generation %d failed (exits %s); restarting at world %d "
            "in %.2fs (%d/%d)", view.generation, bad, n, delay,
            restarts, policy.max_restarts,
        )
        sleep(delay)
