"""Packed 1-bit serving for the MoE family (BnnMoEMLP) — the last
binarized family without a freeze path (infer.py: MLP, infer_conv.py:
conv, infer_transformer.py: attention; here: routed experts).

No reference counterpart (the reference has no MoE — SURVEY §2.2). What
folds and what stays live follows the family's own routing contract
(models/moe.py):

  * first BinarizedDense: ±1 weights on raw pixels (first-layer
    passthrough), then **BN as an eval-time affine, NOT a threshold** —
    the fp32 router consumes the continuous hardtanh stream, so the
    classic binarize∘BN folding is unavailable for this BN;
  * router: plain fp32 Dense + softmax + the SAME ``topk_dispatch`` the
    live model routes with (identical tie-breaking, capacity math);
  * experts: per-expert (D, Do) latents → stacked pre-packed bitplanes,
    one packed GEMM per expert (E is small and static: the loop unrolls
    under jit);
  * the path into the fp32 head IS foldable: binarize(hardtanh(BN(y)))
    collapses to the per-channel threshold compare (infer._bn_sign_fn)
    because nothing else reads that stream — integer GEMM → threshold →
    ±1 bits → packed head GEMM, no BN/activation tensors materialized;
  * the load-balance aux loss is train-only (a sow) and drops out of the
    frozen graph entirely.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from .infer import _bn_affine_fn, _bn_sign_fn
from .models.moe import BnnMoEMLP
from .ops.binarize import binarize_ste
from .ops.routing import topk_dispatch
from .ops.xnor_gemm import prepack_weights, xnor_matmul_packed


def _freeze_moe_tensors(model: BnnMoEMLP, variables: Dict) -> Dict[str, Any]:
    params = variables["params"]
    stats = variables["batch_stats"]
    expert_w = params["BinarizedExperts_0"]["w"]      # (E, D, Do)
    packed = [prepack_weights(binarize_ste(w)) for w in expert_w]
    wp = jnp.stack([p[0] for p in packed])            # (E, KWp, Np)
    head_wp, head_k, head_n = prepack_weights(
        binarize_ste(params["BinarizedDense_1"]["kernel"])
    )
    frozen: Dict[str, Any] = {
        "family": "bnn-moe-mlp",
        "num_experts": model.num_experts,
        "router_k": model.router_k,
        "capacity_factor": model.capacity_factor,
        "w1": binarize_ste(params["BinarizedDense_0"]["kernel"]),
        "b1": params["BinarizedDense_0"]["bias"],
        "bn0": {"params": dict(params["BatchNorm_0"]),
                "stats": dict(stats["BatchNorm_0"])},
        "router_w": params["router"]["kernel"],
        "router_b": params["router"]["bias"],
        "experts_wp": wp,
        "experts_k": packed[0][1],
        "experts_n": packed[0][2],
        "experts_b": params["BinarizedExperts_0"]["b"],
        "bn1": {"params": dict(params["BatchNorm_1"]),
                "stats": dict(stats["BatchNorm_1"])},
        "head_wp": head_wp,
        "head_k": head_k,
        "head_n": head_n,
        "head_b": params["BinarizedDense_1"]["bias"],
    }
    latent = (
        int(params["BinarizedDense_0"]["kernel"].size)
        + int(expert_w.size)
        + int(params["BinarizedDense_1"]["kernel"].size)
    ) * 4
    packed_bytes = (
        int(frozen["w1"].size) + int(wp.size) + int(head_wp.size)
    ) * 4
    frozen["info"] = {
        "family": "bnn-moe-mlp",
        "latent_fp32_weight_bytes": latent,
        "frozen_weight_bytes": packed_bytes,
        "compression": round(latent / packed_bytes, 2),
        "packed_layers": ["BinarizedExperts_0", "BinarizedDense_1"],
    }
    return frozen


def _build_moe_apply(frozen: Dict[str, Any], interpret: bool) -> Callable:
    num_experts = int(frozen["num_experts"])
    router_k = int(frozen["router_k"])
    capacity_factor = float(frozen["capacity_factor"])
    w1 = jnp.asarray(frozen["w1"], jnp.float32)       # disk: int8 ±1
    b1 = jnp.asarray(frozen["b1"], jnp.float32)
    bn0 = _bn_affine_fn(frozen["bn0"]["params"], frozen["bn0"]["stats"])
    router_w = jnp.asarray(frozen["router_w"], jnp.float32)
    router_b = jnp.asarray(frozen["router_b"], jnp.float32)
    experts_wp = jnp.asarray(frozen["experts_wp"])
    ek, en = int(frozen["experts_k"]), int(frozen["experts_n"])
    experts_b = jnp.asarray(frozen["experts_b"], jnp.float32)
    bn1_sign = _bn_sign_fn(frozen["bn1"]["params"], frozen["bn1"]["stats"])
    head_wp = jnp.asarray(frozen["head_wp"])
    hk, hn = int(frozen["head_k"]), int(frozen["head_n"])
    head_b = jnp.asarray(frozen["head_b"], jnp.float32)

    def apply_fn(images: jnp.ndarray) -> jnp.ndarray:
        x = images.reshape(images.shape[0], -1).astype(jnp.float32)
        x = jnp.dot(x, w1, preferred_element_type=jnp.float32) + b1
        x = jax.nn.hard_tanh(bn0(x))                  # affine BN, hardtanh
        gates = jax.nn.softmax(x @ router_w + router_b)
        t = x.shape[0]
        capacity = max(
            1, math.ceil(capacity_factor * t * router_k / num_experts)
        )
        dispatch, combine = topk_dispatch(gates, capacity, router_k)
        ex_in = jnp.einsum("tec,td->ecd", dispatch, x)
        xb = binarize_ste(ex_in)                      # (E, C, D)
        ex_out = jnp.stack([
            xnor_matmul_packed(
                xb[e], experts_wp[e], ek, en, interpret=interpret
            ) + experts_b[e]
            for e in range(num_experts)
        ])
        y = jnp.einsum("tec,ecd->td", combine, ex_out)
        bits = bn1_sign(y)                            # BN+hardtanh+sign
        logits = xnor_matmul_packed(
            bits, head_wp, hk, hn, interpret=interpret
        ) + head_b
        return jax.nn.log_softmax(logits)

    return jax.jit(apply_fn)


def freeze_bnn_moe(
    model: BnnMoEMLP, variables: Dict, *, interpret: bool = False
) -> Tuple[Callable, Dict[str, Any]]:
    """Freeze a trained BnnMoEMLP into packed routed inference; matches
    ``model.apply(variables, x, train=False)`` (backend="xla" models —
    the exactness caveats of the other families apply)."""
    frozen = _freeze_moe_tensors(model, variables)
    return _build_moe_apply(frozen, interpret), frozen["info"]
