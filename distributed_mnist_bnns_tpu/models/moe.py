"""Binarized Mixture-of-Experts MLP — the trainable MoE model family.

No reference counterpart (the reference's models are dense MLPs/CNNs,
SURVEY §2.2); this family makes the expert-parallel op stack
(parallel/expert_parallel.py) trainable end to end through the generic
Trainer: a flagship-style binarized MLP whose middle layer is a top-2
routed bank of ``binarized_expert`` FFNs (sign(x) @ sign(W_e) + b_e)
with the Switch-Transformer load-balancing auxiliary loss.

Wiring conventions:
  * the router is a plain fp32 Dense named ``router`` OUTSIDE any
    ``Binarized*`` module path — latent_clamp_mask matches the
    "Binarized" prefix, and router weights are ordinary fp32 params
    that must not be clamped to [-1, 1];
  * expert latents live under ``BinarizedExperts_0`` so the clamp mask
    and the latent-master STE semantics apply to them exactly as to
    BinarizedDense kernels;
  * the auxiliary loss is sown into the ``intermediates`` collection
    under the name ``aux_loss`` (already scaled by ``aux_coef``); the
    train step body collects every such sow into the total loss
    (train/trainer.py make_step_body), so any model can contribute
    auxiliary objectives the same way;
  * routing uses the same ``topk_dispatch`` the expert-parallel path
    uses, with per-batch capacity ``ceil(capacity_factor * T * k / E)``
    — the dense einsum formulation here is numerically the n_shards=1
    oracle of ``moe_reference``, so the sharded deployment is covered by
    the EP-vs-dense equality tests.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.binarize import binarize_ste
from ..ops.routing import load_balance_loss, topk_dispatch
from ..ops.xnor_gemm import Backend, binary_matmul
from .layers import BinarizedDense


class BinarizedExperts(nn.Module):
    """A routed bank of binarized FFN experts.

    Applies (dispatch, combine) routing tensors produced by the caller:
    params are the stacked per-expert latents (E, D, Do) — the layout the
    'expert' mesh axis shards in the EP deployment."""

    num_experts: int
    features: int

    @nn.compact
    def __call__(self, x, dispatch, combine):
        d = x.shape[-1]
        scale = d**-0.5
        w = self.param(
            "w",
            lambda key, shape: jax.random.uniform(
                key, shape, minval=-scale, maxval=scale
            ),
            (self.num_experts, d, self.features),
        )
        b = self.param(
            "b", nn.initializers.zeros_init(),
            (self.num_experts, self.features),
        )
        ex_in = jnp.einsum("tec,td->ecd", dispatch, x)   # (E, C, D)
        xb = binarize_ste(ex_in)

        def expert(w_e, b_e, x_e):
            return binary_matmul(x_e, binarize_ste(w_e)) + b_e

        ex_out = jax.vmap(expert)(w, b, xb)              # (E, C, Do)
        return jnp.einsum("tec,ecd->td", combine, ex_out)


class BnnMoEMLP(nn.Module):
    """Flagship-style binarized MLP with a top-2 MoE middle layer."""

    hidden: int = 512
    num_experts: int = 8
    expert_features: int = 512
    num_classes: int = 10
    router_k: int = 2
    capacity_factor: float = 1.25
    aux_coef: float = 0.01
    backend: Backend | None = None
    ste: str = "identity"

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        x = x.reshape(x.shape[0], -1)
        bn = lambda: nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5
        )
        x = BinarizedDense(
            self.hidden, binarize_input=False, ste=self.ste,
            backend=self.backend,
        )(x)
        x = bn()(x)
        x = nn.hard_tanh(x)

        # fp32 router on the continuous stream (sign patterns carry too
        # little information to route on).
        gates = jax.nn.softmax(nn.Dense(self.num_experts, name="router")(x))
        t = x.shape[0]
        capacity = max(
            1,
            math.ceil(
                self.capacity_factor * t * self.router_k / self.num_experts
            ),
        )
        dispatch, combine = topk_dispatch(gates, capacity, self.router_k)
        self.sow(
            "intermediates", "aux_loss",
            self.aux_coef * load_balance_loss(gates),
        )
        y = BinarizedExperts(
            self.num_experts, self.expert_features,
            name="BinarizedExperts_0",
        )(x, dispatch, combine)
        x = bn()(y)
        x = nn.hard_tanh(x)
        x = BinarizedDense(
            self.num_classes, ste=self.ste, backend=self.backend,
        )(x)
        return nn.log_softmax(x)


def bnn_moe_mlp(**kw) -> BnnMoEMLP:
    return BnnMoEMLP(**kw)
