from .layers import BinarizedDense, QuantizedDense, BinarizedConv
from .mlp import qnn_mlp_large, BnnMLP, bnn_mlp_large, bnn_mlp_small, fp32_mlp_large
from .convnet import ConvNet
from .cnn import DeepCNN
from .bnn_cnn import BinarizedCNN
from .resnet import XnorResNet, xnor_resnet18, xnor_resnet50
from .transformer import (
    BinarizedLM,
    lm_loss,
    BinarizedSelfAttention,
    BinarizedTransformer,
    TransformerBlock,
    bnn_vit_small,
    bnn_vit_tiny,
)
from .registry import get_model, MODEL_REGISTRY, latent_clamp_mask

__all__ = [
    "BinarizedDense",
    "QuantizedDense",
    "BinarizedConv",
    "BnnMLP",
    "bnn_mlp_large",
    "bnn_mlp_small",
    "qnn_mlp_large",
    "fp32_mlp_large",
    "ConvNet",
    "DeepCNN",
    "BinarizedCNN",
    "XnorResNet",
    "xnor_resnet18",
    "xnor_resnet50",
    "BinarizedSelfAttention",
    "BinarizedTransformer",
    "TransformerBlock",
    "BinarizedLM",
    "lm_loss",
    "bnn_vit_tiny",
    "bnn_vit_small",
    "get_model",
    "MODEL_REGISTRY",
    "latent_clamp_mask",
]
