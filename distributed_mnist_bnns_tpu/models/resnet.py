"""XNOR-ResNet family — the BASELINE.json stretch configs ("CIFAR-10
XNOR-ResNet-18", "ImageNet-1k XNOR-ResNet-50"). Not present in the
reference (its models stop at small MLPs/CNNs, SURVEY §2.2); included to
exceed parity on the binarized-op capability the reference defines.

XNOR-Net conventions (Rastegari et al. 2016):
  * first conv and final classifier stay fp32 (binarizing them costs
    disproportionate accuracy);
  * every other conv is a BinarizedConv (±1 weights/activations, fp32
    latent masters, STE gradients);
  * BN before each binarized conv's sign(), pre-activation style blocks.

TPU-first: NHWC layout, bf16 MXU convs by default, identity shortcuts as
pure adds that XLA fuses into the conv epilogue.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
from flax import linen as nn

from ..ops.xnor_gemm import Backend
from .layers import BinarizedConv


def _twin_conv(block, features, kernel, strides=(1, 1)):
    """The binarized/fp32 twin switch shared by both block types: one
    definition of which kwargs each side gets, so the basic and
    bottleneck blocks' twins cannot drift apart."""
    if not block.binarized:
        return nn.Conv(features, kernel, strides=strides)
    return BinarizedConv(
        features, kernel, strides=strides, ste=block.ste,
        backend=block.backend, scale=block.scale,
    )


class XnorBasicBlock(nn.Module):
    """Pre-activation binarized basic block: BN -> BinConv3x3 -> BN ->
    BinConv3x3 (+ fp32 1x1 projection shortcut on stride/width change)."""

    features: int
    strides: int = 1
    backend: Backend | None = None
    ste: str = "identity"
    scale: bool = False  # XNOR-Net per-channel alpha on binarized convs
    binarized: bool = True  # False: fp32 twin (nn.Conv), same topology

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        bn = lambda: nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5
        )

        def conv(features, kernel, strides=(1, 1)):
            return _twin_conv(self, features, kernel, strides)

        shortcut = x
        y = bn()(x)
        y = conv(
            self.features, (3, 3), strides=(self.strides, self.strides)
        )(y)
        y = bn()(y)
        y = conv(self.features, (3, 3))(y)
        if shortcut.shape[-1] != self.features or self.strides != 1:
            shortcut = nn.Conv(
                self.features, (1, 1),
                strides=(self.strides, self.strides), use_bias=False,
            )(x)
        return y + shortcut


class XnorBottleneckBlock(nn.Module):
    """Pre-activation binarized bottleneck (1x1 -> 3x3 -> 1x1, x4 expand)."""

    features: int
    strides: int = 1
    backend: Backend | None = None
    ste: str = "identity"
    scale: bool = False  # XNOR-Net per-channel alpha on binarized convs
    binarized: bool = True  # False: fp32 twin (nn.Conv), same topology

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        bn = lambda: nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5
        )

        def conv(features, kernel, strides=(1, 1)):
            return _twin_conv(self, features, kernel, strides)

        out_ch = self.features * 4
        shortcut = x
        y = bn()(x)
        y = conv(self.features, (1, 1))(y)
        y = bn()(y)
        y = conv(
            self.features, (3, 3), strides=(self.strides, self.strides)
        )(y)
        y = bn()(y)
        y = conv(out_ch, (1, 1))(y)
        if shortcut.shape[-1] != out_ch or self.strides != 1:
            shortcut = nn.Conv(
                out_ch, (1, 1), strides=(self.strides, self.strides),
                use_bias=False,
            )(x)
        return y + shortcut


class XnorResNet(nn.Module):
    """Binarized ResNet over NHWC images (CIFAR stem by default)."""

    stage_sizes: Sequence[int] = (2, 2, 2, 2)  # ResNet-18
    bottleneck: bool = False
    num_classes: int = 10
    stem_features: int = 64
    cifar_stem: bool = True  # 3x3/1 stem (CIFAR); else 7x7/2 + maxpool
    backend: Backend | None = None
    ste: str = "identity"
    scale: bool = False  # XNOR-Net per-channel alpha on binarized convs
    binarized: bool = True  # False: fp32 twin — the accuracy denominator
                            # for the conv binarization gap (RESULTS.md)

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        # fp32 stem (XNOR-Net keeps the first conv full precision).
        if self.cifar_stem:
            x = nn.Conv(self.stem_features, (3, 3), use_bias=False)(x)
        else:
            x = nn.Conv(
                self.stem_features, (7, 7), strides=(2, 2), use_bias=False
            )(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        block = XnorBottleneckBlock if self.bottleneck else XnorBasicBlock
        for stage, n_blocks in enumerate(self.stage_sizes):
            features = self.stem_features * (2**stage)
            for b in range(n_blocks):
                strides = 2 if stage > 0 and b == 0 else 1
                x = block(
                    features, strides=strides, ste=self.ste,
                    backend=self.backend, scale=self.scale,
                    binarized=self.binarized,
                )(x, train=train)
        x = nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5
        )(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return nn.Dense(self.num_classes)(x)  # fp32 classifier


def xnor_resnet18(**kw) -> XnorResNet:
    return XnorResNet(stage_sizes=(2, 2, 2, 2), bottleneck=False, **kw)


def xnor_resnet50(**kw) -> XnorResNet:
    return XnorResNet(stage_sizes=(3, 4, 6, 3), bottleneck=True,
                      cifar_stem=False, **kw)


def fp32_resnet18(**kw) -> XnorResNet:
    """xnor_resnet18 with binarization removed — the conv-family
    accuracy denominator (same role as fp32_mlp_large / fp32_vit_tiny)."""
    kw.setdefault("binarized", False)
    return xnor_resnet18(**kw)
