"""Model registry + the latent-weight clamp mask.

The registry plays the role of the reference's per-script hardcoded ``Net``
classes (SURVEY.md §2.2): one name -> constructor map covering every model
family the reference defines, plus the binarized CNN stretch config.

``latent_clamp_mask`` identifies which parameters are binarized-layer
latents: exactly the params the reference tags with ``.org`` (kernel *and*
bias of BinarizeLinear/BinarizeConv2d — both get ``.org`` in
models/binarized_modules.py:77-84) and therefore clamps to [-1, 1] after
each optimizer step (mnist-dist2.py:135-137).
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import jax
from flax import linen as nn

from .bnn_cnn import BinarizedCNN
from .cnn import DeepCNN
from .convnet import ConvNet
from .mlp import bnn_mlp_large, bnn_mlp_small, fp32_mlp_large, qnn_mlp_large
from .moe import bnn_moe_mlp
from .resnet import fp32_resnet18, xnor_resnet18, xnor_resnet50
from .transformer import (
    bnn_vit_small,
    bnn_vit_tiny,
    fp32_vit_small,
    fp32_vit_tiny,
)

MODEL_REGISTRY: Dict[str, Callable[..., nn.Module]] = {
    # flagship BNN MLPs (mnist-dist2.py:46-76 / mnist-dist3.py:40-70)
    "bnn-mlp-large": bnn_mlp_large,
    "bnn-mlp-small": bnn_mlp_small,
    # fp32 twin of the flagship (accuracy yardstick, BASELINE.md north star)
    "fp32-mlp-large": fp32_mlp_large,
    # k-bit quantized twin (the reference's Quantize op, made live)
    "qnn-mlp-large": qnn_mlp_large,
    # fp32 baselines (mnist-dist.py:31-51, mnist-cnn server.py:7-52)
    "convnet": ConvNet,
    "deep-cnn": DeepCNN,
    # binarized CNN (BASELINE.json config; uses BinarizeConv2d capability)
    "bnn-cnn": BinarizedCNN,
    # stretch configs (BASELINE.json): binarized ResNets
    "xnor-resnet18": xnor_resnet18,
    "xnor-resnet50": xnor_resnet50,
    # fp32 twin of the resnet stretch (conv binarization-gap denominator)
    "fp32-resnet18": fp32_resnet18,
    # binarized transformers (no reference counterpart: the attention
    # stack — flash/ring attention — as a trainable model family)
    "bnn-vit-tiny": bnn_vit_tiny,
    "bnn-vit-small": bnn_vit_small,
    # fp32 twins of the vit family (binarization-gap denominators,
    # mirroring fp32-mlp-large's role for the MLP family)
    "fp32-vit-tiny": fp32_vit_tiny,
    "fp32-vit-small": fp32_vit_small,
    # binarized MoE (no reference counterpart: the expert-parallel stack
    # — top-2 routing + load-balance aux loss — as a trainable family)
    "bnn-moe-mlp": bnn_moe_mlp,
}


def get_model(name: str, **kwargs: Any) -> nn.Module:
    try:
        return MODEL_REGISTRY[name](**kwargs)
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; available: {sorted(MODEL_REGISTRY)}"
        ) from None


def latent_clamp_mask(params: Any) -> Any:
    """Bool pytree: True for every leaf living under a Binarized* module.

    Works on a flax params dict; matching is by module path component
    prefix ("BinarizedDense_0", "BinarizedConv_1", ...), so both kernel and
    bias of binarized layers are selected — the same set the reference
    restores/clamps via the ``.org`` protocol (mnist-dist2.py:131-137).
    """
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def is_latent(path) -> bool:
        # Match the leaf's immediate owner module, not any ancestor: an
        # fp32-twin nn.Dense nested under BinarizedSelfAttention_0 must
        # NOT be clamped (binarized=False swaps the children, but the
        # attention wrapper keeps its class-derived name). Every real
        # latent is directly owned by a Binarized* module
        # (BinarizedDense/BinarizedConv kernels+biases, the
        # BinarizedExperts_0 stacked bank).
        keys = [getattr(p, "key", "") for p in path if hasattr(p, "key")]
        return len(keys) >= 2 and keys[-2].startswith("Binarized")

    mask_flat = [is_latent(path) for path, _ in flat]
    treedef = jax.tree_util.tree_structure(params)
    return jax.tree_util.tree_unflatten(treedef, mask_flat)
