"""BNN MLP family — the reference's flagship model.

Parity target: ``Net`` in mnist-dist2.py:46-76 (large, infl_ratio=3:
784 -> BinLinear 3072 -> BN -> Hardtanh -> BinLinear 1536 -> BN -> Hardtanh
-> BinLinear 768 -> Dropout(0.3) -> BN -> Hardtanh -> fp32 Linear 10 ->
LogSoftmax) and mnist-dist3.py:40-70 (small: width 192 throughout).

Quirks preserved on purpose (documented, reference-faithful):
  * dropout is applied *before* the third BatchNorm (mnist-dist2.py:72-74);
  * the final fp32 Linear feeds LogSoftmax even though training uses
    cross-entropy on top (mnist-dist2.py:75,124) — harmless (shift
    invariance), kept so logits match the reference's scale;
  * the first BinarizedDense consumes raw pixels un-binarized — the
    explicit-flag version of the reference's input.size(1)==784 check
    (models/binarized_modules.py:75).

BatchNorm uses per-replica statistics under data parallelism (DDP default in
the reference; SURVEY.md §7 "hard parts"), torch-default eps=1e-5 and an
EMA equivalent to torch momentum=0.1 (flax momentum=0.9).
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
from flax import linen as nn

from ..ops.xnor_gemm import Backend
from .layers import BinarizedDense, QuantizedDense


class BnnMLP(nn.Module):
    """Binarized MLP with fp32 first/last-layer boundaries per the reference.

    ``binarized=False`` swaps every BinarizedDense for an ordinary fp32
    nn.Dense while keeping the topology byte-for-byte identical (same BN /
    Hardtanh / dropout-before-bn3 ordering) — the accuracy yardstick for
    BASELINE.md's "accuracy within 0.5%" north star: the measured gap is
    exactly the cost of binarizing, not of an architecture difference."""

    hidden: Sequence[int] = (3072, 1536, 768)
    num_classes: int = 10
    dropout_rate: float = 0.3
    backend: Backend | None = None
    ste: str = "identity"
    stochastic: bool = False  # stochastic activation binarization (train-time)
    binarized: bool = True

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        x = x.reshape(x.shape[0], -1)
        h1, h2, h3 = self.hidden
        stoch = self.stochastic and train
        bn = lambda: nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5
        )

        def dense(features: int, first: bool = False) -> nn.Module:
            if not self.binarized:
                return nn.Dense(features)
            # first layer: raw pixels in, not binarized (passthrough).
            return BinarizedDense(
                features,
                binarize_input=not first,
                ste=self.ste,
                backend=self.backend,
                stochastic=stoch and not first,
            )

        x = dense(h1, first=True)(x)
        x = bn()(x)
        x = nn.hard_tanh(x)
        x = dense(h2)(x)
        x = bn()(x)
        x = nn.hard_tanh(x)
        x = dense(h3)(x)
        # Reference order: dropout THEN bn3 (mnist-dist2.py:72-74).
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = bn()(x)
        x = nn.hard_tanh(x)
        x = nn.Dense(self.num_classes)(x)  # fp32 classifier head
        return nn.log_softmax(x)


class QnnMLP(nn.Module):
    """k-bit quantized twin of the flagship topology (QuantizedDense in
    place of BinarizedDense, same BN/Hardtanh/dropout-before-bn3 ordering)
    — makes the reference's dead ``Quantize`` op (models/
    binarized_modules.py:56-63) a live, trainable model family covering
    the middle ground between the 1-bit BNNs and the fp32 twin."""

    hidden: Sequence[int] = (3072, 1536, 768)
    num_classes: int = 10
    dropout_rate: float = 0.3
    num_bits: int = 8
    stochastic: bool = False  # stochastic rounding (train-time)

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        x = x.reshape(x.shape[0], -1)
        h1, h2, h3 = self.hidden
        stoch = self.stochastic and train
        bn = lambda: nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5
        )

        def dense(features: int, first: bool = False) -> nn.Module:
            return QuantizedDense(
                features,
                num_bits=self.num_bits,
                quant_input=not first,
                stochastic=stoch and not first,
            )

        x = dense(h1, first=True)(x)
        x = bn()(x)
        x = nn.hard_tanh(x)
        x = dense(h2)(x)
        x = bn()(x)
        x = nn.hard_tanh(x)
        x = dense(h3)(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = bn()(x)
        x = nn.hard_tanh(x)
        x = nn.Dense(self.num_classes)(x)
        return nn.log_softmax(x)


def qnn_mlp_large(infl_ratio: int = 3, **kw) -> QnnMLP:
    return QnnMLP(
        hidden=(1024 * infl_ratio, 512 * infl_ratio, 256 * infl_ratio), **kw
    )


def fp32_mlp_large(infl_ratio: int = 3, **kw) -> BnnMLP:
    """The flagship topology with binarization removed (see BnnMLP)."""
    return BnnMLP(
        hidden=(1024 * infl_ratio, 512 * infl_ratio, 256 * infl_ratio),
        binarized=False,
        **kw,
    )


def bnn_mlp_large(infl_ratio: int = 3, **kw) -> BnnMLP:
    """784 -> 1024r -> 512r -> 256r -> 10 (mnist-dist2.py:48-76, r=3)."""
    return BnnMLP(hidden=(1024 * infl_ratio, 512 * infl_ratio, 256 * infl_ratio), **kw)


def bnn_mlp_small(infl_ratio: int = 3, **kw) -> BnnMLP:
    """784 -> 64r -> 64r -> 64r -> 10 (mnist-dist3.py:42-70, r=3)."""
    w = 64 * infl_ratio
    return BnnMLP(hidden=(w, w, w), **kw)
