"""Deeper fp32 CNN — parity with the reference's ``CNN``
(mnist-cnn server.py:7-52, byte-identical client):

  3 conv blocks 1->32->64->128 (3x3, SAME, ReLU, MaxPool 2x2; the 3rd pool
  has padding=1, so 28 -> 14 -> 7 -> 4 spatially) ->
  FC 2048->625 (Xavier init, ReLU, Dropout keep_prob=0.5) ->
  FC 625->10 (Xavier init).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


def _max_pool_padded(x: jnp.ndarray) -> jnp.ndarray:
    """2x2/2 max pool with torch-style padding=1 (pad both sides, floor):
    7x7 -> 4x4, matching MaxPool2d(2, 2, padding=1) in the reference."""
    return jax.lax.reduce_window(
        x,
        -jnp.inf,
        jax.lax.max,
        window_dimensions=(1, 2, 2, 1),
        window_strides=(1, 2, 2, 1),
        padding=((0, 0), (1, 1), (1, 1), (0, 0)),
    )


class DeepCNN(nn.Module):
    num_classes: int = 10
    dropout_rate: float = 0.5  # torch keep_prob=0.5 -> drop 0.5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        if x.ndim == 2:
            x = x.reshape(x.shape[0], 28, 28, 1)
        x = x.astype(self.dtype)
        for i, features in enumerate((32, 64, 128)):
            x = nn.Conv(features, (3, 3), padding="SAME", dtype=self.dtype)(x)
            x = nn.relu(x)
            if i < 2:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = _max_pool_padded(x)
        x = x.reshape(x.shape[0], -1).astype(jnp.float32)  # (B, 4*4*128)
        x = nn.Dense(625, kernel_init=nn.initializers.xavier_uniform())(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(
            self.num_classes, kernel_init=nn.initializers.xavier_uniform()
        )(x)
