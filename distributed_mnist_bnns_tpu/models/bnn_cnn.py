"""Fully-binarized CNN for MNIST (XNOR-Net style) — the "MNIST
BinarizeConv2d CNN" configuration from BASELINE.json. The reference defines
BinarizeConv2d (models/binarized_modules.py:87-107) but never uses it in a
model; this model family exercises it end to end the TPU way: binarized
convs lower to bf16 MXU convs or to patch-extraction + bitplane XNOR GEMM.

First conv consumes raw pixels (binarize_input=False — the explicit form of
the reference's RGB/first-layer channel check, models/binarized_modules.py:94).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from ..ops.xnor_gemm import Backend
from .layers import BinarizedConv, BinarizedDense


class BinarizedCNN(nn.Module):
    num_classes: int = 10
    widths: tuple[int, int] = (64, 128)
    hidden: int = 1024
    backend: Backend | None = None
    ste: str = "identity"
    stochastic: bool = False  # stochastic activation binarization (train-time)

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        if x.ndim == 2:
            x = x.reshape(x.shape[0], 28, 28, 1)
        stoch = self.stochastic and train
        bn = lambda: nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5
        )
        w1, w2 = self.widths
        x = BinarizedConv(
            w1, (3, 3), binarize_input=False, ste=self.ste, backend=self.backend
        )(x)
        x = bn()(x)
        x = nn.hard_tanh(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))  # 28 -> 14
        x = BinarizedConv(w2, (3, 3), ste=self.ste, backend=self.backend,
                          stochastic=stoch)(x)
        x = bn()(x)
        x = nn.hard_tanh(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))  # 14 -> 7
        x = x.reshape(x.shape[0], -1)
        x = BinarizedDense(self.hidden, ste=self.ste, backend=self.backend,
                           stochastic=stoch)(x)
        x = bn()(x)
        x = nn.hard_tanh(x)
        x = nn.Dense(self.num_classes)(x)
        return nn.log_softmax(x)
