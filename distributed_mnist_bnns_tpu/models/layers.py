"""Binarized Flax layers with fp32 latent ("master") parameters.

Parity targets: the reference's BinarizeLinear / BinarizeConv2d
(models/binarized_modules.py:68-85, 87-107). Semantics preserved:

  * fp32 latent kernel/bias live as the *only* stored parameters; the ±1
    binarized view is re-derived on every forward (the reference's
    weight.org / weight.data pair collapses to latent params + a pure
    function — no aliasing, no in-place mutation).
  * inputs are binarized before the GEMM *except* for first layers fed raw
    data. The reference keys this on channel count (input.size(1)==784 for
    linear, ==3 for conv — models/binarized_modules.py:75,94), a fragile
    heuristic; here it is an explicit ``binarize_input`` flag per layer
    (SURVEY.md §7 "hard parts").
  * bias stays fp32 and is added after the binary GEMM
    (models/binarized_modules.py:83-84, 103-106).
  * gradients: straight-through — ``binarize_ste`` (identity by default,
    matching the training dynamics of the reference's data-swap trick;
    "hardtanh" mode available for the textbook BNN STE).

TPU-first notes: the GEMM runs on a selectable backend (bf16 MXU by
default — exact for ±1 operands — or the XNOR-popcount bitplane path; see
ops/xnor_gemm.py). Convolutions lower to lax.conv_general_dilated in
bf16 (MXU) or to patch-extraction + binary GEMM for the bitplane backend.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.binarize import STEMode, binarize, binarize_ste, quantize
from ..ops.xnor_gemm import (
    Backend,
    binary_conv2d,
    binary_matmul,
    conv_padding_correction,
    conv_patch_weight,
    get_default_backend,
)

Dtype = Any


def _latent_init(scale: float = 1.0) -> Callable:
    """LeCun-uniform style init for latent weights, kept in [-1, 1] so the
    clamp projection is a no-op at step 0 (torch's default kaiming-uniform
    for the reference's layer sizes also lands well inside [-1, 1])."""

    def init(key, shape, dtype=jnp.float32):
        fan_in = 1
        for d in shape[:-1]:
            fan_in *= d
        bound = min(1.0, scale / (fan_in**0.5))
        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return init


def _layer_backend(mdl: nn.Module) -> Backend:
    """Resolve this layer's GEMM backend. The int8/xnor/pallas_xnor paths
    assume ±1 operands (int8 casts truncate, the bitplane paths re-sign the
    activations), so first layers fed raw (non-binarized) activations fall
    back to the fp32 xla path — matching the reference's fp32 first layer
    (models/binarized_modules.py:75). bf16 is left as-is: choosing it for
    raw inputs is a deliberate AMP-style precision trade, exact on ±1."""
    backend = mdl.backend or get_default_backend()
    if not mdl.binarize_input and backend in ("int8", "xnor", "pallas_xnor"):
        return "xla"
    return backend


def _binarize_activations(
    mdl: nn.Module, x: jnp.ndarray, stochastic: bool, ste: STEMode
) -> jnp.ndarray:
    """Activation binarization shared by Dense/Conv: stochastic (reference
    quant_mode='stoch', models/binarized_modules.py:12-15) when requested
    and a 'binarize' rng stream is available, deterministic sign otherwise.
    The Trainer always threads a 'binarize' rng, so stochastic=True is live
    in the real training path."""
    if stochastic and mdl.has_rng("binarize"):
        return binarize(x, "stoch", ste=ste, key=mdl.make_rng("binarize"))
    return binarize_ste(x, ste)


class BinarizedDense(nn.Module):
    """y = binarize(x) @ binarize(W_latent) + b_fp32.

    Attributes:
      features: output width.
      binarize_input: binarize the activations entering this layer
        (False for the first layer on raw pixels — the explicit version of
        the reference's ``input.size(1) != 784`` check).
      ste: "identity" (reference parity) or "hardtanh".
      backend: GEMM backend override (None -> global default).
    """

    features: int
    binarize_input: bool = True
    use_bias: bool = True
    ste: STEMode = "identity"
    stochastic: bool = False  # reference quant_mode='stoch' on activations
    scale: bool = False       # XNOR-Net per-channel analytic scaling
    backend: Backend | None = None
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        kernel = self.param(
            "kernel",
            _latent_init(),
            (x.shape[-1], self.features),
            self.param_dtype,
        )
        if self.binarize_input:
            x = _binarize_activations(self, x, self.stochastic, self.ste)
        wb = binarize_ste(kernel, self.ste)
        lead = x.shape[:-1]
        backend = _layer_backend(self)
        y = binary_matmul(x.reshape(-1, x.shape[-1]), wb, backend)
        y = y.reshape(*lead, self.features)
        if self.scale:
            # XNOR-Net: rescale the ±1 GEMM by the analytic per-output-
            # channel alpha = mean|W_latent| (Rastegari et al.) —
            # recomputed from the latent masters each forward (no new
            # params), gradient flows to the latents through both the
            # STE'd sign and the real |.|-mean. Beyond reference parity
            # (the reference never rescales, models/binarized_modules.py).
            y = y * jnp.abs(kernel).mean(axis=0)
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros_init(), (self.features,), self.param_dtype
            )
            y = y + bias
        return y


class QuantizedDense(nn.Module):
    """k-bit fixed-point dense layer: y = Q_k(x) @ Q_k(W_latent) + b_fp32.

    Puts the ``quantize`` op (ops/binarize.py — the reference's ``Quantize``,
    models/binarized_modules.py:56-63, which its scripts never used and
    whose stochastic branch was broken) into the model zoo as a live
    layer: fp32 latent masters quantized to ``num_bits`` signed fixed
    point each forward with identity-STE gradients, the same latent-
    master pattern as the binarized layers (1-bit is ``BinarizedDense``;
    this covers the k-bit middle ground). Latents live under a module
    name starting with "Quantized", so the [-1, 1] clamp projection does
    NOT apply (quantize clamps to its own 2^(b-1) grid).

    ``quant_input=False`` passes raw activations through (first-layer
    semantics); stochastic rounding uses the 'binarize' rng stream when
    present (train-time), deterministic rounding otherwise.
    """

    features: int
    num_bits: int = 8
    quant_input: bool = True
    use_bias: bool = True
    stochastic: bool = False
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        kernel = self.param(
            "kernel",
            _latent_init(),
            (x.shape[-1], self.features),
            self.param_dtype,
        )

        def q(v, key=None):
            if key is not None:
                return quantize(v, "stoch", self.num_bits, key=key)
            return quantize(v, "det", self.num_bits)

        if self.quant_input:
            x = q(
                x,
                self.make_rng("binarize")
                if self.stochastic and self.has_rng("binarize") else None,
            )
        wq = q(kernel)
        y = jnp.dot(x, wq, preferred_element_type=jnp.float32)
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros_init(), (self.features,),
                self.param_dtype,
            )
            y = y + bias
        return y


class BinarizedConv(nn.Module):
    """NHWC binarized conv: conv(binarize(x), binarize(W_latent)) + b_fp32.

    Reference parity: BinarizeConv2d (models/binarized_modules.py:87-107) —
    input binarized unless this is a raw-image first layer, fp32 latent
    kernel binarized each forward, fp32 bias broadcast over space after the
    conv. Data layout is NHWC (TPU-native), not the reference's NCHW.
    """

    features: int
    kernel_size: Sequence[int] = (3, 3)
    strides: Sequence[int] = (1, 1)
    padding: str | Sequence[tuple[int, int]] = "SAME"
    binarize_input: bool = True
    use_bias: bool = True
    ste: STEMode = "identity"
    stochastic: bool = False
    scale: bool = False       # XNOR-Net per-channel analytic scaling
    backend: Backend | None = None
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        kh, kw = self.kernel_size
        in_ch = x.shape[-1]
        kernel = self.param(
            "kernel",
            _latent_init(),
            (kh, kw, in_ch, self.features),
            self.param_dtype,
        )
        if self.binarize_input:
            x = _binarize_activations(self, x, self.stochastic, self.ste)
        wb = binarize_ste(kernel, self.ste)

        backend = _layer_backend(self)
        if backend in ("xnor", "pallas_xnor"):
            # Patch-extraction (im2col) + bitplane GEMM: each output pixel's
            # receptive field becomes a K=kh*kw*in_ch ±1 dot product.
            patches = jax.lax.conv_general_dilated_patches(
                x,
                filter_shape=(kh, kw),
                window_strides=tuple(self.strides),
                padding=self.padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )  # (N, Ho, Wo, kh*kw*in_ch) — but channel-major patch order
            n, ho, wo, k = patches.shape
            # Canonical im2col weight ordering — shared with the frozen
            # serving path (ops.conv_patch_weight).
            wmat = conv_patch_weight(wb)
            y = binary_matmul(patches.reshape(-1, k), wmat, backend)
            y = y.reshape(n, ho, wo, self.features)
            pads_zeros = (
                self.padding != "VALID"
                if isinstance(self.padding, str)
                else any(p != 0 for pair in self.padding for p in pair)
            )
            if pads_zeros:
                # Zero-padded border taps enter the bitplane GEMM as -1
                # (pack_bits maps x > 0 to bit 1) instead of contributing
                # nothing; add back the weights they spuriously subtracted
                # (ops.conv_padding_correction — shared with the frozen
                # serving path). stop_gradient: binary_matmul's VJP
                # differentiates the exact {-1, 0, +1} patches, so the
                # gradient is already correct without the correction term.
                y = y + jax.lax.stop_gradient(
                    conv_padding_correction(
                        jnp.sum(wb, axis=2), x.shape[1:3],
                        tuple(self.strides), self.padding,
                    )
                )
        else:
            dtype = {"bf16": jnp.bfloat16, "int8": jnp.int8}.get(
                backend, x.dtype
            )
            padding = (
                self.padding if isinstance(self.padding, str)
                else tuple(tuple(p) for p in self.padding)
            )
            y = binary_conv2d(
                x, wb, tuple(self.strides), padding, dtype
            )
        if self.scale:
            # XNOR-Net alpha per output channel: mean |W_latent| over the
            # (kh, kw, in) receptive field (see BinarizedDense.scale).
            y = y * jnp.abs(kernel).mean(axis=(0, 1, 2))
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros_init(), (self.features,), self.param_dtype
            )
            y = y + bias
        return y
