"""Binarized vision transformer — the attention-model family.

No reference counterpart (the reference stops at MLPs/CNNs — SURVEY §2.2);
this family exists so the framework's attention stack (ops/flash_attention,
parallel/ring_attention) is exercised by an actual trainable model rather
than op-level tests only, following the BNN-transformer recipe
(BinaryViT/BiT-style): **weights of every projection are binarized with
fp32 latent masters, activations entering binarized GEMMs are sign()-
binarized, while the attention core (softmax over scores) and the
normalization/residual stream stay full precision** — binarizing the
softmax input distribution collapses it, so no published binary
transformer does.

Reference-semantics carried over from the MLP family:
  * patch embedding consumes raw pixels -> ``binarize_input=False``
    (the reference's fp32 first layer, models/binarized_modules.py:75);
  * the classifier head is a plain fp32 Dense (the reference's fp32 last
    layer, mnist-dist2.py:70);
  * all Binarized* latents are clamped to [-1, 1] by the trainer's
    projection (latent_clamp_mask matches them by module-path prefix);
    pos-embed / LayerNorm / head params are ordinary fp32 and unclamped.

TPU-first: attention="flash" runs the Pallas flash kernel (L and D should
be tile-aligned; MNIST 16 tokens / CIFAR 64 tokens at head_dim 32/64 are);
attention="xla" is the exact einsum oracle (default — XLA fuses it well at
these tiny sequence lengths and it runs everywhere, incl. CPU tests).
Sequence parallelism for long sequences uses the same flash local step via
parallel/ring_attention at the op level.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.binarize import STEMode
from ..ops.flash_attention import flash_attention
from ..ops.xnor_gemm import Backend
from .layers import BinarizedDense


def _attend_xla(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, causal: bool = False
) -> jnp.ndarray:
    """Exact (B, T, H, D) softmax attention — the oracle path."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        t = scores.shape[-1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class BinarizedSelfAttention(nn.Module):
    """Multi-head self-attention with binarized q/k/v/out projections.

    ``attention_fn`` overrides the core with any (q, k, v) -> out callable
    over (B, T, H, D) — e.g. ``parallel.make_ring_attention(mesh)`` to run
    the token axis sequence-parallel over a 'seq' mesh (the projections
    and residual stream are per-token and need no communication, so the
    ring handles all of SP's cross-device traffic)."""

    embed_dim: int
    num_heads: int
    attention: str = "xla"  # "xla" | "flash" | "flash_interpret"
    attention_fn: Optional[Callable] = None
    causal: bool = False
    ste: STEMode = "identity"
    stochastic: bool = False
    scale: bool = False  # XNOR-Net per-channel alpha on binarized GEMMs
    backend: Optional[Backend] = None
    binarized: bool = True  # False: fp32 twin (nn.Dense projections),
                            # topology otherwise identical (see BnnMLP)

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b, t, _ = x.shape
        if self.embed_dim % self.num_heads:
            raise ValueError(
                f"embed_dim {self.embed_dim} not divisible by "
                f"num_heads {self.num_heads}"
            )
        head_dim = self.embed_dim // self.num_heads

        # NOTE: binarized submodules keep their auto-generated
        # BinarizedDense_N names — latent_clamp_mask selects latents by
        # the "Binarized" module-path prefix (models/registry.py).
        def dense():
            if not self.binarized:
                return nn.Dense(self.embed_dim)
            return BinarizedDense(
                self.embed_dim,
                binarize_input=True,
                ste=self.ste,
                stochastic=self.stochastic,
                scale=self.scale,
                backend=self.backend,
            )

        q = dense()(x).reshape(b, t, self.num_heads, head_dim)
        k = dense()(x).reshape(b, t, self.num_heads, head_dim)
        v = dense()(x).reshape(b, t, self.num_heads, head_dim)
        if self.attention_fn is not None:
            # attention_fn owns its masking (build a causal ring with
            # make_ring_attention(mesh, causal=True) for causal SP).
            out = self.attention_fn(q, k, v)
        elif self.attention == "xla":
            out = _attend_xla(q, k, v, causal=self.causal)
        elif self.attention in ("flash", "flash_interpret"):
            out = flash_attention(
                q, k, v, causal=self.causal,
                interpret=self.attention == "flash_interpret",
            )
        else:
            raise ValueError(
                f"unknown attention {self.attention!r} "
                "(have: xla, flash, flash_interpret)"
            )
        # Observability hook: the continuous attention-core output, before
        # the out-projection sign()-binarizes it (apply with
        # mutable/capture "intermediates" to read it — the right
        # equivalence target when comparing attention implementations,
        # since downstream sign bits legitimately flip on few-ulp diffs).
        self.sow("intermediates", "attn_core", out)
        return dense()(out.reshape(b, t, self.embed_dim))


class TransformerBlock(nn.Module):
    """Pre-norm block shared by the vit and the LM:
    x += attn(LN(x)); x += mlp(LN(x)) with the MLP as BinarizedDense ->
    Hardtanh -> BinarizedDense.

    NOTE: deliberately NOT named Binarized* — latent_clamp_mask matches
    module-path components by that prefix, and this block also holds
    LayerNorm params that must stay unclamped; the BinarizedDense /
    BinarizedSelfAttention children re-establish the prefix for the
    latents."""

    embed_dim: int
    num_heads: int
    mlp_ratio: int = 2
    dropout: float = 0.0
    attention: str = "xla"
    attention_fn: Optional[Callable] = None
    causal: bool = False
    ste: STEMode = "identity"
    stochastic: bool = False
    scale: bool = False
    backend: Optional[Backend] = None
    binarized: bool = True
    binarized_attention: Optional[bool] = None  # None: follow `binarized`;
    # False with binarized=True = the partial-binarization ablation
    # (fp32 q/k/v/out, binary MLP blocks — RESULTS.md gap attribution)

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        def dense(features):
            if not self.binarized:
                return nn.Dense(features)
            return BinarizedDense(
                features,
                binarize_input=True,
                ste=self.ste,
                stochastic=self.stochastic,
                scale=self.scale,
                backend=self.backend,
            )

        y = nn.LayerNorm(name="ln_attn")(x)
        attn_binarized = (
            self.binarized
            if self.binarized_attention is None
            else self.binarized_attention
        )
        y = BinarizedSelfAttention(
            self.embed_dim,
            self.num_heads,
            attention=self.attention,
            attention_fn=self.attention_fn,
            causal=self.causal,
            ste=self.ste,
            stochastic=self.stochastic,
            scale=self.scale,
            backend=self.backend,
            binarized=attn_binarized,
        )(y)
        if self.dropout:
            y = nn.Dropout(self.dropout, deterministic=not train)(y)
        x = x + y
        y = nn.LayerNorm(name="ln_mlp")(x)
        y = dense(self.embed_dim * self.mlp_ratio)(y)
        y = nn.hard_tanh(y)
        y = dense(self.embed_dim)(y)
        if self.dropout:
            y = nn.Dropout(self.dropout, deterministic=not train)(y)
        return x + y


class BinarizedTransformer(nn.Module):
    """Patch-embedding binarized transformer classifier.

    Pre-norm blocks: x += attn(LN(x)); x += mlp(LN(x)) with the MLP as
    BinarizedDense -> Hardtanh -> BinarizedDense (the framework's BNN
    activation idiom, mnist-dist2.py:51-74's Hardtanh role). Mean-pooled
    tokens feed the fp32 head.
    """

    num_classes: int = 10
    patch_size: int = 7
    embed_dim: int = 128
    depth: int = 2
    num_heads: int = 4
    mlp_ratio: int = 2
    dropout: float = 0.0
    attention: str = "xla"
    attention_fn: Optional[Callable] = None  # e.g. a ring-attention fn
    ste: STEMode = "identity"
    stochastic: bool = False
    scale: bool = False  # XNOR-Net per-channel alpha on binarized GEMMs
    backend: Optional[Backend] = None
    binarized: bool = True  # False: fp32 twin — accuracy yardstick for
                            # the transformer binarization gap (RESULTS.md)
    binarized_attention: Optional[bool] = None  # partial-binarization
                            # ablation (see TransformerBlock)

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        b, h, w, c = x.shape
        p = self.patch_size
        if h % p or w % p:
            raise ValueError(
                f"input {h}x{w} not divisible by patch_size {p}"
            )
        nh, nw = h // p, w // p
        # (B, H, W, C) -> (B, T, p*p*C) without any host-side reshaping.
        x = x.reshape(b, nh, p, nw, p, c)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, nh * nw, p * p * c)
        # Patch embedding on raw pixels: binarized weights, fp32 input
        # (first-layer passthrough semantics).
        if self.binarized:
            x = BinarizedDense(  # patch embedding (auto-named: clamp mask)
                self.embed_dim,
                binarize_input=False,
                ste=self.ste,
                backend=self.backend,
            )(x)
        else:
            x = nn.Dense(self.embed_dim)(x)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, nh * nw, self.embed_dim),
        )
        x = x + pos
        for _ in range(self.depth):
            x = TransformerBlock(
                self.embed_dim,
                self.num_heads,
                mlp_ratio=self.mlp_ratio,
                dropout=self.dropout,
                attention=self.attention,
                attention_fn=self.attention_fn,
                ste=self.ste,
                stochastic=self.stochastic,
                scale=self.scale,
                backend=self.backend,
                binarized=self.binarized,
                binarized_attention=self.binarized_attention,
            )(x, train=train)
        x = nn.LayerNorm(name="ln_head")(x).mean(axis=1)
        x = nn.Dense(self.num_classes, name="head")(x)
        return nn.log_softmax(x)


class BinarizedLM(nn.Module):
    """Causal binarized language model — the sequence-modeling twin of the
    vit: fp32 token + position embeddings (binarizing an embedding lookup
    would collapse the vocabulary to sign patterns), pre-norm causal
    blocks with binarized q/k/v/out and MLP projections, fp32 LN + head
    over the vocab. ``attention="flash"`` runs the causal Pallas kernel;
    an ``attention_fn`` built with ``make_ring_attention(mesh,
    causal=True)`` runs the context window sequence-parallel — the
    long-context path of this framework, exercised by a trainable model.

    Returns (B, T, vocab) next-token log-probs (position t predicts
    token t+1; shift-and-mask lives in ``lm_loss``)."""

    vocab: int = 256
    max_len: int = 256
    embed_dim: int = 128
    depth: int = 2
    num_heads: int = 4
    mlp_ratio: int = 2
    dropout: float = 0.0
    attention: str = "xla"
    attention_fn: Optional[Callable] = None
    ste: STEMode = "identity"
    stochastic: bool = False
    scale: bool = False
    backend: Optional[Backend] = None
    binarized: bool = True  # False: fp32 twin (see BinarizedTransformer)
    binarized_attention: Optional[bool] = None  # partial-binarization
                            # ablation (see TransformerBlock)

    @nn.compact
    def __call__(self, tokens: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        b, t = tokens.shape
        if t > self.max_len:
            raise ValueError(f"sequence length {t} > max_len {self.max_len}")
        x = nn.Embed(self.vocab, self.embed_dim, name="tok_embed")(tokens)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, self.max_len, self.embed_dim),
        )
        x = x + pos[:, :t]
        for _ in range(self.depth):
            x = TransformerBlock(
                self.embed_dim,
                self.num_heads,
                mlp_ratio=self.mlp_ratio,
                dropout=self.dropout,
                attention=self.attention,
                attention_fn=self.attention_fn,
                causal=True,
                ste=self.ste,
                stochastic=self.stochastic,
                scale=self.scale,
                backend=self.backend,
                binarized=self.binarized,
                binarized_attention=self.binarized_attention,
            )(x, train=train)
        x = nn.LayerNorm(name="ln_head")(x)
        return nn.log_softmax(nn.Dense(self.vocab, name="head")(x))


def lm_loss(log_probs: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Next-token cross entropy: position t's log-probs score token t+1
    (the final position has no target and is dropped)."""
    targets = tokens[:, 1:]
    lp = log_probs[:, :-1]
    return -jnp.take_along_axis(lp, targets[..., None], axis=-1).mean()


def bnn_vit_tiny(**kw) -> BinarizedTransformer:
    """MNIST-sized: 7x7 patches -> 16 tokens, 128-dim, 2 blocks."""
    kw.setdefault("patch_size", 7)
    kw.setdefault("embed_dim", 128)
    kw.setdefault("depth", 2)
    kw.setdefault("num_heads", 4)
    return BinarizedTransformer(**kw)


def bnn_vit_small(**kw) -> BinarizedTransformer:
    """CIFAR-sized: 4x4 patches -> 64 tokens, 256-dim, 4 blocks."""
    kw.setdefault("patch_size", 4)
    kw.setdefault("embed_dim", 256)
    kw.setdefault("depth", 4)
    kw.setdefault("num_heads", 8)
    return BinarizedTransformer(**kw)


def fp32_vit_tiny(**kw) -> BinarizedTransformer:
    """bnn-vit-tiny with binarization removed — the accuracy denominator
    for the transformer binarization gap (same role as fp32_mlp_large)."""
    kw.setdefault("binarized", False)
    return bnn_vit_tiny(**kw)


def fp32_vit_small(**kw) -> BinarizedTransformer:
    """bnn-vit-small with binarization removed (fp32 twin)."""
    kw.setdefault("binarized", False)
    return bnn_vit_small(**kw)
