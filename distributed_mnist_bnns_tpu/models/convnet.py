"""fp32 ConvNet — parity with the reference's ``ConvNet``
(mnist-dist.py:31-51 and its byte-identical duplicates in mnist.py,
mnist-mixed.py, the change-master/node pairs):

  Conv(1->16, 5x5, pad 2) -> BN -> ReLU -> MaxPool(2)
  Conv(16->32, 5x5, pad 2) -> BN -> ReLU -> MaxPool(2)
  Linear(7*7*32 -> 10)

TPU-native: NHWC layout, bf16 compute optional via dtype, MXU-friendly conv
shapes; no binarization anywhere (this is the fp32 baseline model).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn


class ConvNet(nn.Module):
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        if x.ndim == 2:  # (B, 784) -> (B, 28, 28, 1)
            x = x.reshape(x.shape[0], 28, 28, 1)
        x = x.astype(self.dtype)
        for features in (16, 32):
            x = nn.Conv(features, (5, 5), padding=2, dtype=self.dtype)(x)
            x = nn.BatchNorm(
                use_running_average=not train, momentum=0.9, epsilon=1e-5,
                dtype=self.dtype,
            )(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)  # (B, 7*7*32)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(
            x.astype(jnp.float32)
        )
