"""ctypes bindings for the native data-runtime (idx decode, normalize,
bitpack). Builds libdmbnative.so on first use via make/g++ (toolchain is in
the image; no pybind11 needed), with a transparent pure-python fallback —
set DMB_TPU_NO_NATIVE=1 to force the fallback.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libdmbnative.so")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("DMB_TPU_NO_NATIVE"):
        return None
    # Rebuild when the .so is absent or older than its source (a stale
    # .so from an older revision would miss symbols). Build to a per-pid
    # temp and os.replace it in — atomic, so concurrent processes (multi-
    # host training, pytest-xdist) never dlopen a half-written file; at
    # worst they compile redundantly.
    srcs = [
        os.path.join(_DIR, "idx_loader.cpp"),
        os.path.join(_DIR, "batch_pool.cpp"),
    ]
    try:
        need = (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < max(
                    os.path.getmtime(s) for s in srcs
                ))
        if need:
            tmp = f"{_SO}.tmp.{os.getpid()}"
            subprocess.run(
                ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-pthread",
                 *srcs, "-o", tmp],
                check=True, capture_output=True, timeout=120,
            )
            os.replace(tmp, _SO)
    except Exception as e:  # pragma: no cover - toolchain always present
        log.debug("native build failed (%s); using python fallback", e)
        if not os.path.exists(_SO):
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:  # pragma: no cover
        log.debug("native load failed (%s)", e)
        return None
    try:
        _bind(lib)
    except AttributeError as e:  # pragma: no cover - stale .so, no rebuild
        log.debug("native symbols missing (%s); using python fallback", e)
        return None
    _lib = lib
    return _lib


def _bind(lib: ctypes.CDLL) -> None:
    lib.idx_header.restype = ctypes.c_int
    lib.idx_header.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64)]
    lib.idx_read_u8.restype = ctypes.c_int
    lib.idx_read_u8.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ]
    lib.u8_normalize.restype = ctypes.c_int
    lib.u8_normalize.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64, ctypes.c_float, ctypes.c_float,
    ]
    lib.pack_bits_pm1.restype = ctypes.c_int
    lib.pack_bits_pm1.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
    ]
    lib.cifar_bin_decode.restype = ctypes.c_int
    lib.cifar_bin_decode.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ]
    lib.bp_create.restype = ctypes.c_void_p
    lib.bp_create.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int, ctypes.c_int,
    ]
    lib.bp_next.restype = ctypes.c_int64
    lib.bp_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.bp_destroy.restype = None
    lib.bp_destroy.argtypes = [ctypes.c_void_p]


def available() -> bool:
    return _load() is not None


def load_idx_native(path: str) -> Optional[np.ndarray]:
    """Native idx parse; None if the library is unavailable or the file is
    gzipped (the python path handles .gz)."""
    if path.endswith(".gz"):
        return None
    lib = _load()
    if lib is None:
        return None
    dims = (ctypes.c_int64 * 4)()
    ndim = lib.idx_header(path.encode(), dims)
    if ndim < 1:
        raise ValueError(f"{path}: bad idx file (code {ndim})")
    shape = tuple(int(dims[i]) for i in range(ndim))
    out = np.empty(int(np.prod(shape)), dtype=np.uint8)
    rc = lib.idx_read_u8(
        path.encode(), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.size,
    )
    if rc != 0:
        raise ValueError(f"{path}: idx payload read failed (code {rc})")
    return out.reshape(shape)


def normalize_native(images_u8: np.ndarray, mean: float, std: float
                     ) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    flat = np.ascontiguousarray(images_u8, dtype=np.uint8).reshape(-1)
    out = np.empty(flat.size, dtype=np.float32)
    lib.u8_normalize(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        flat.size, ctypes.c_float(mean), ctypes.c_float(1.0 / std),
    )
    return out.reshape(images_u8.shape)


def pack_bits_native(x: np.ndarray) -> Optional[np.ndarray]:
    """(rows, k) ±1 float32 -> (rows, ceil(k/32)) int32 bitplanes."""
    lib = _load()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.float32)
    rows, k = x.shape
    kw = -(-k // 32)
    out = np.empty((rows, kw), dtype=np.int32)
    lib.pack_bits_pm1(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        rows, k, kw,
    )
    return out


class BatchPool:
    """Threaded native batch loader: worker threads gather shuffled
    batches (the random-access images[idx] row gathers) into a ring of
    slots ahead of the consumer — torch DataLoader's num_workers
    capability, for this framework's host pipeline. Delivery is strictly
    in index order (deterministic regardless of thread scheduling).

    Iterate to receive (images (batch, *item_shape) float32,
    labels (batch,) int32) — caller-owned arrays, no lifetime coupling to
    the pool. Use as a context manager or rely on __del__ to join the
    workers. Falls back at construction: ``BatchPool.create`` returns
    None when the native library is unavailable.
    """

    def __init__(self, lib, images: np.ndarray, labels: np.ndarray,
                 idx: np.ndarray, batch: int, n_threads: int,
                 n_slots: int):
        self._lib = lib
        # Keep references: the pool reads these buffers from C++.
        self._images = np.ascontiguousarray(images, dtype=np.float32)
        self._labels = np.ascontiguousarray(labels, dtype=np.int32)
        self._item_shape = self._images.shape[1:]
        feat = int(np.prod(self._item_shape)) if self._item_shape else 1
        self._feat = feat
        self._batch = int(batch)
        idx = np.ascontiguousarray(idx, dtype=np.int64)
        self._n_batches = len(idx) // self._batch
        idx = idx[: self._n_batches * self._batch]
        if idx.size and (idx.min() < 0 or idx.max() >= len(self._labels)):
            raise IndexError("batch indices out of range")
        self._handle = lib.bp_create(
            self._images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self._labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            feat,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            self._n_batches, self._batch, n_threads, n_slots,
        )
        if not self._handle:
            raise RuntimeError("bp_create failed")

    @classmethod
    def create(cls, images, labels, idx, batch, *, n_threads: int = 2,
               n_slots: int = 4) -> Optional["BatchPool"]:
        lib = _load()
        if lib is None:
            return None
        return cls(lib, images, labels, idx, batch, n_threads, n_slots)

    def __iter__(self):
        while True:
            images = np.empty((self._batch, self._feat), np.float32)
            labels = np.empty((self._batch,), np.int32)
            b = self._lib.bp_next(
                self._handle,
                images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            )
            if b < 0:
                return
            yield images.reshape((self._batch, *self._item_shape)), labels

    def close(self) -> None:
        if self._handle:
            self._lib.bp_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        # jg: disable=JG005 -- teardown-time close; globals may be gone
        except Exception:
            pass


def cifar_bin_decode_native(path: str, n_records: int):
    """Decode a CIFAR-10 binary batch to (images_nhwc_u8, labels_i32);
    None if the library is unavailable. The CHW->HWC transpose is fused
    into the single file-read pass."""
    lib = _load()
    if lib is None:
        return None
    images = np.empty((n_records, 32, 32, 3), dtype=np.uint8)
    labels_u8 = np.empty((n_records,), dtype=np.uint8)
    rc = lib.cifar_bin_decode(
        path.encode(),
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        labels_u8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n_records,
    )
    if rc != 0:
        raise ValueError(f"{path}: cifar bin decode failed (code {rc})")
    return images, labels_u8.astype(np.int32)
