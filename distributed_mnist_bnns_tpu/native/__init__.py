"""ctypes bindings for the native data-runtime (idx decode, normalize,
bitpack). Builds libdmbnative.so on first use via make/g++ (toolchain is in
the image; no pybind11 needed), with a transparent pure-python fallback —
set DMB_TPU_NO_NATIVE=1 to force the fallback.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libdmbnative.so")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("DMB_TPU_NO_NATIVE"):
        return None
    # Rebuild when the .so is absent or older than its source (a stale
    # .so from an older revision would miss symbols). Build to a per-pid
    # temp and os.replace it in — atomic, so concurrent processes (multi-
    # host training, pytest-xdist) never dlopen a half-written file; at
    # worst they compile redundantly.
    src = os.path.join(_DIR, "idx_loader.cpp")
    try:
        need = (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(src))
        if need:
            tmp = f"{_SO}.tmp.{os.getpid()}"
            subprocess.run(
                ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", src,
                 "-o", tmp],
                check=True, capture_output=True, timeout=120,
            )
            os.replace(tmp, _SO)
    except Exception as e:  # pragma: no cover - toolchain always present
        log.debug("native build failed (%s); using python fallback", e)
        if not os.path.exists(_SO):
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:  # pragma: no cover
        log.debug("native load failed (%s)", e)
        return None
    try:
        _bind(lib)
    except AttributeError as e:  # pragma: no cover - stale .so, no rebuild
        log.debug("native symbols missing (%s); using python fallback", e)
        return None
    _lib = lib
    return _lib


def _bind(lib: ctypes.CDLL) -> None:
    lib.idx_header.restype = ctypes.c_int
    lib.idx_header.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64)]
    lib.idx_read_u8.restype = ctypes.c_int
    lib.idx_read_u8.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ]
    lib.u8_normalize.restype = ctypes.c_int
    lib.u8_normalize.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64, ctypes.c_float, ctypes.c_float,
    ]
    lib.pack_bits_pm1.restype = ctypes.c_int
    lib.pack_bits_pm1.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
    ]
    lib.cifar_bin_decode.restype = ctypes.c_int
    lib.cifar_bin_decode.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ]


def available() -> bool:
    return _load() is not None


def load_idx_native(path: str) -> Optional[np.ndarray]:
    """Native idx parse; None if the library is unavailable or the file is
    gzipped (the python path handles .gz)."""
    if path.endswith(".gz"):
        return None
    lib = _load()
    if lib is None:
        return None
    dims = (ctypes.c_int64 * 4)()
    ndim = lib.idx_header(path.encode(), dims)
    if ndim < 1:
        raise ValueError(f"{path}: bad idx file (code {ndim})")
    shape = tuple(int(dims[i]) for i in range(ndim))
    out = np.empty(int(np.prod(shape)), dtype=np.uint8)
    rc = lib.idx_read_u8(
        path.encode(), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.size,
    )
    if rc != 0:
        raise ValueError(f"{path}: idx payload read failed (code {rc})")
    return out.reshape(shape)


def normalize_native(images_u8: np.ndarray, mean: float, std: float
                     ) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    flat = np.ascontiguousarray(images_u8, dtype=np.uint8).reshape(-1)
    out = np.empty(flat.size, dtype=np.float32)
    lib.u8_normalize(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        flat.size, ctypes.c_float(mean), ctypes.c_float(1.0 / std),
    )
    return out.reshape(images_u8.shape)


def pack_bits_native(x: np.ndarray) -> Optional[np.ndarray]:
    """(rows, k) ±1 float32 -> (rows, ceil(k/32)) int32 bitplanes."""
    lib = _load()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.float32)
    rows, k = x.shape
    kw = -(-k // 32)
    out = np.empty((rows, kw), dtype=np.int32)
    lib.pack_bits_pm1(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        rows, k, kw,
    )
    return out


def cifar_bin_decode_native(path: str, n_records: int):
    """Decode a CIFAR-10 binary batch to (images_nhwc_u8, labels_i32);
    None if the library is unavailable. The CHW->HWC transpose is fused
    into the single file-read pass."""
    lib = _load()
    if lib is None:
        return None
    images = np.empty((n_records, 32, 32, 3), dtype=np.uint8)
    labels_u8 = np.empty((n_records,), dtype=np.uint8)
    rc = lib.cifar_bin_decode(
        path.encode(),
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        labels_u8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n_records,
    )
    if rc != 0:
        raise ValueError(f"{path}: cifar bin decode failed (code {rc})")
    return images, labels_u8.astype(np.int32)
