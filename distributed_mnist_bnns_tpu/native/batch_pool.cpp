// Native threaded batch pool: the host-side data-loading runtime.
//
// Role: the part of the reference stack that torch DataLoader's C++
// worker machinery provided (num_workers gather/collate threads feeding
// the train loop; the reference used single-worker defaults,
// mnist-dist2.py:96-108, but the capability lives in torch's native
// layer). Here: N worker threads gather shuffled batches
// (images[idx[b*batch..]] row gathers — the random-access-heavy part of
// the pipeline) into a ring of preallocated slots, ahead of the
// consumer; bp_next blocks until the *in-order* next batch is ready and
// memcpys it into caller-owned memory, so Python-side lifetime is
// trivial and delivery order is deterministic regardless of worker
// scheduling (DistributedSampler-reproducibility semantics).
//
// C ABI for ctypes (no pybind11 in this image). Returns: bp_next gives
// the batch ordinal (>=0), BP_DONE when exhausted, negative on error.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

constexpr int kFree = 0;     // slot writable by the worker owning its turn
constexpr int kReady = 1;    // slot filled, waiting for the consumer

struct Slot {
    std::mutex mu;
    std::condition_variable cv;
    int state = kFree;
    int64_t epoch = -1;  // which ring-lap filled it (slot reuse ordering)
    std::vector<float> images;
    std::vector<int32_t> labels;
};

struct BatchPool {
    const float* images;
    const int32_t* labels;
    int64_t feat;
    std::vector<int64_t> idx;  // own a copy: caller's array may be freed
    int64_t n_batches;
    int64_t batch;
    int n_slots;
    std::atomic<int64_t> ticket{0};  // next batch a worker will produce
    int64_t consumed = 0;            // next batch the consumer will take
    std::vector<Slot> slots;
    std::vector<std::thread> workers;
    std::atomic<bool> stop{false};

    void worker() {
        for (;;) {
            const int64_t b = ticket.fetch_add(1);
            if (b >= n_batches || stop.load()) return;
            Slot& s = slots[b % n_slots];
            const int64_t lap = b / n_slots;
            std::unique_lock<std::mutex> lk(s.mu);
            // Wait for the previous lap's batch in this slot to be
            // consumed (ring backpressure).
            s.cv.wait(lk, [&] {
                return stop.load() || (s.state == kFree && s.epoch == lap - 1);
            });
            if (stop.load()) return;
            lk.unlock();  // gather without holding the lock
            const int64_t* sel = idx.data() + b * batch;
            float* di = s.images.data();
            for (int64_t r = 0; r < batch; ++r)
                std::memcpy(di + r * feat, images + sel[r] * feat,
                            (size_t)feat * sizeof(float));
            int32_t* dl = s.labels.data();
            for (int64_t r = 0; r < batch; ++r) dl[r] = labels[sel[r]];
            lk.lock();
            s.state = kReady;
            s.epoch = lap;
            s.cv.notify_all();
        }
    }
};

}  // namespace

extern "C" {

const int BP_DONE = -1;

// images: (n_items, feat) float32 row-major; labels: (n_items,) int32;
// idx: n_batches*batch gather indices (row order defines the batches).
// The images/labels pointers must stay valid for the pool's lifetime
// (the Python wrapper keeps references); idx is copied.
void* bp_create(const float* images, const int32_t* labels,
                int64_t feat, const int64_t* idx, int64_t n_batches,
                int64_t batch, int n_threads, int n_slots) {
    if (n_batches < 0 || batch <= 0 || feat <= 0 || n_threads <= 0 ||
        n_slots <= 0)
        return nullptr;
    auto* p = new BatchPool();
    p->images = images;
    p->labels = labels;
    p->feat = feat;
    p->idx.assign(idx, idx + n_batches * batch);
    p->n_batches = n_batches;
    p->batch = batch;
    p->n_slots = n_slots;
    p->slots = std::vector<Slot>(n_slots);
    for (auto& s : p->slots) {
        s.images.resize((size_t)(batch * feat));
        s.labels.resize((size_t)batch);
    }
    for (int t = 0; t < n_threads; ++t)
        p->workers.emplace_back([p] { p->worker(); });
    return p;
}

// Blocks until the next in-order batch is ready, copies it into
// out_images (batch*feat floats) / out_labels (batch int32), frees the
// slot. Returns the batch ordinal, or BP_DONE when all batches have been
// delivered.
int64_t bp_next(void* pool, float* out_images, int32_t* out_labels) {
    auto* p = static_cast<BatchPool*>(pool);
    if (p->consumed >= p->n_batches) return BP_DONE;
    const int64_t b = p->consumed++;
    Slot& s = p->slots[b % p->n_slots];
    const int64_t lap = b / p->n_slots;
    std::unique_lock<std::mutex> lk(s.mu);
    s.cv.wait(lk, [&] { return s.state == kReady && s.epoch == lap; });
    std::memcpy(out_images, s.images.data(),
                (size_t)(p->batch * p->feat) * sizeof(float));
    std::memcpy(out_labels, s.labels.data(),
                (size_t)p->batch * sizeof(int32_t));
    s.state = kFree;
    s.cv.notify_all();
    return b;
}

void bp_destroy(void* pool) {
    auto* p = static_cast<BatchPool*>(pool);
    p->stop.store(true);
    for (auto& s : p->slots) {
        std::lock_guard<std::mutex> lk(s.mu);
        s.cv.notify_all();
    }
    for (auto& t : p->workers) t.join();
    delete p;
}

}  // extern "C"
