// Native data-runtime: MNIST idx decoding, normalization, and host-side
// bitplane packing.
//
// Role: the fast host-side IO/preprocessing layer under data/mnist.py —
// the part of the reference stack that lived in torch's native DataLoader
// machinery (the reference itself ships no first-party native code; its
// native layer is all third-party torch/NCCL/Gloo — SURVEY §2 note). The
// TPU compute path stays JAX/XLA/Pallas; this library feeds it.
//
// Exposed via a C ABI for ctypes (no pybind11 in this image). All
// functions return 0 on success, negative errno-style codes on failure.

#include <cstdint>
#include <cstdio>
#include <cstring>

extern "C" {

// Parse an idx header: magic 0x0000080N (u8 data, N dims), big-endian dims.
// dims_out must hold >= 4 entries. Returns ndim, or <0 on error.
int idx_header(const char* path, int64_t* dims_out) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return -1;
    unsigned char h[4];
    if (std::fread(h, 1, 4, f) != 4) { std::fclose(f); return -2; }
    if (h[0] != 0 || h[1] != 0 || h[2] != 0x08) { std::fclose(f); return -3; }
    int ndim = h[3];
    if (ndim < 1 || ndim > 4) { std::fclose(f); return -3; }
    for (int i = 0; i < ndim; ++i) {
        unsigned char d[4];
        if (std::fread(d, 1, 4, f) != 4) { std::fclose(f); return -2; }
        dims_out[i] = (int64_t(d[0]) << 24) | (int64_t(d[1]) << 16) |
                      (int64_t(d[2]) << 8) | int64_t(d[3]);
    }
    std::fclose(f);
    return ndim;
}

// Read the u8 payload (after the header) into out[0..n).
int idx_read_u8(const char* path, uint8_t* out, int64_t n) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return -1;
    unsigned char h[4];
    if (std::fread(h, 1, 4, f) != 4) { std::fclose(f); return -2; }
    int ndim = h[3];
    if (std::fseek(f, 4 + 4 * ndim, SEEK_SET) != 0) { std::fclose(f); return -2; }
    size_t got = std::fread(out, 1, (size_t)n, f);
    std::fclose(f);
    return got == (size_t)n ? 0 : -4;
}

// out[i] = (in[i]/255 - mean) / std  — the torchvision Normalize transform.
int u8_normalize(const uint8_t* in, float* out, int64_t n, float mean,
                 float inv_std) {
    const float scale = inv_std / 255.0f;
    const float shift = -mean * inv_std;
    for (int64_t i = 0; i < n; ++i) out[i] = in[i] * scale + shift;
    return 0;
}

// Pack ±1 floats into int32 bitplanes along the last axis:
// bit = 1 <=> value > 0; rows x kw output words, zero-padded tail.
// Matches ops/bitpack.py pack_bits convention exactly.
int pack_bits_pm1(const float* in, int32_t* out, int64_t rows, int64_t k,
                  int64_t kw) {
    for (int64_t r = 0; r < rows; ++r) {
        const float* src = in + r * k;
        int32_t* dst = out + r * kw;
        std::memset(dst, 0, (size_t)kw * sizeof(int32_t));
        for (int64_t j = 0; j < k; ++j) {
            if (src[j] > 0.0f)
                dst[j >> 5] |= (int32_t)(1u << (j & 31));
        }
    }
    return 0;
}

// Decode one CIFAR-10 binary batch file: n records of [label u8 |
// 3072 u8 pixels in CHW (plane-major) order]. Writes labels[0..n) and
// images in NHWC order (n*32*32*3) — the transpose the python loader does
// with numpy, fused into the single read pass here.
int cifar_bin_decode(const char* path, uint8_t* images_nhwc,
                     uint8_t* labels, int64_t n_records) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return -1;
    const int64_t HW = 32 * 32, REC = 1 + 3 * HW;
    unsigned char rec[1 + 3 * 32 * 32];
    for (int64_t r = 0; r < n_records; ++r) {
        if (std::fread(rec, 1, (size_t)REC, f) != (size_t)REC) {
            std::fclose(f);
            return -4;
        }
        labels[r] = rec[0];
        uint8_t* dst = images_nhwc + r * 3 * HW;
        for (int64_t px = 0; px < HW; ++px) {
            dst[px * 3 + 0] = rec[1 + 0 * HW + px];
            dst[px * 3 + 1] = rec[1 + 1 * HW + px];
            dst[px * 3 + 2] = rec[1 + 2 * HW + px];
        }
    }
    std::fclose(f);
    return 0;
}

}  // extern "C"
