"""Content-addressed store of ahead-of-time compiled XLA executables.

Every trainer run and server boot used to pay online tracing + XLA
compilation before the first useful step; the persistent ``.jax_cache``
only removes the XLA half (tracing and lowering still run, and the
cache key is internal to jax). This store goes the rest of the way:
``cli aot build`` lowers and compiles the known jit signatures
(``jax.jit(...).lower().compile()`` +
``jax.experimental.serialize_executable``) into on-disk entries keyed
by everything that determines the compiled program:

    (program name, code revision of the traced modules, jax version,
     backend platform, mesh, input avals (shapes + dtypes), closure
     constants (artifact digest), static config extras)

A boot that *hits* deserializes the executable and installs it — no
trace, no lowering, no compile (``aot_hit`` event). A miss (or any
corrupt / incompatible entry) falls back to the normal trace+compile
and re-banks the result (``aot_miss`` / ``aot_bank``), so the store is
self-healing: the worst case is exactly today's cold start.

Robustness follows ``load_checkpoint_resilient``'s digest-verify-then-
act discipline: the payload's sha256 is checked against the manifest
BEFORE deserialization, and any failure (truncated payload, manifest
parse error, jax/backEnd incompatibility surfacing as a deserialize
error) quarantines the entry (renamed ``*.quarantined``) and emits a
loud ``aot_fallback`` event with the reason — boot never crashes on a
bad entry, and the bad bytes are kept aside for post-mortems instead
of being retried forever.

``jax.export`` (StableHLO) is deliberately NOT the wire format here:
it is portable across jax versions but re-compiles at load time, which
is the cost this store exists to remove. ``serialize_executable``
pickles the backend-serialized *executable* — zero compile on load, at
the price of keying on jax version + platform (which the key does).

Layout::

    <root>/<name>/<digest>.bin    pickle: {payload, in_tree, out_tree}
    <root>/<name>/<digest>.json   manifest: key fields + payload sha256

The manifest is written LAST (tmp + atomic rename for both files), so
a crash mid-bank leaves an orphan ``.bin`` that ``gc`` collects, never
a manifest pointing at missing/short bytes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

HITS_TOTAL = "aot_hits_total"
MISSES_TOTAL = "aot_misses_total"
BANKS_TOTAL = "aot_banks_total"
FALLBACKS_TOTAL = "aot_fallbacks_total"

_SCHEMA_V = 1


def format_avals(tree: Any) -> str:
    """Canonical string for a pytree of arrays / ShapeDtypeStructs:
    ``f32[8,784];i32[8]`` in flattening order. Part of the cache key —
    any shape or dtype change must miss."""
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    parts = []
    for leaf in leaves:
        shape = ",".join(str(int(d)) for d in leaf.shape)
        parts.append(f"{jax.dtypes.canonicalize_dtype(leaf.dtype).name}"
                     f"[{shape}]")
    return ";".join(parts)


def canonical_extra(extra: Dict[str, Any]) -> str:
    """Deterministic JSON for the static-config key component."""
    return json.dumps(extra, sort_keys=True, separators=(",", ":"),
                      default=str)


def sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


@dataclasses.dataclass(frozen=True)
class AotKey:
    """Everything that determines the compiled program. Two keys with
    equal digests MUST be interchangeable executables; anything that
    changes the traced computation (code, constants, shapes, static
    config) or its validity (jax version, backend, mesh) is a field."""

    name: str           # logical program: classifier_predict, lm_decode…
    code_rev: str       # programs.current_code_rev(name)
    jax_version: str
    backend: str        # jax.default_backend() at build time
    avals: str          # format_avals of the input signature
    mesh: str = ""      # "" = no mesh; else "axis=size,…" canonical form
    consts: str = ""    # digest of baked-in constants (artifact bytes)
    extra: str = ""     # canonical_extra of static config

    @property
    def digest(self) -> str:
        blob = json.dumps(dataclasses.asdict(self), sort_keys=True,
                          separators=(",", ":")).encode()
        return sha256_hex(blob)

    def asdict(self) -> Dict[str, str]:
        return dataclasses.asdict(self)


def make_key(
    name: str,
    *,
    avals: Any,
    consts: str = "",
    mesh: str = "",
    extra: Optional[Dict[str, Any]] = None,
    code_rev: Optional[str] = None,
) -> AotKey:
    """Build an :class:`AotKey` with the environment fields (jax
    version, backend) and the program's code revision filled in."""
    import jax

    if code_rev is None:
        from .programs import current_code_rev

        code_rev = current_code_rev(name)
    return AotKey(
        name=name,
        code_rev=code_rev,
        jax_version=jax.__version__,
        backend=jax.default_backend(),
        avals=avals if isinstance(avals, str) else format_avals(avals),
        mesh=mesh,
        consts=consts,
        extra=canonical_extra(extra or {}),
    )


class AotStore:
    """On-disk executable store (see module docstring).

    ``telemetry`` (an obs Telemetry/EventLog) receives the
    ``aot_hit`` / ``aot_miss`` / ``aot_bank`` / ``aot_fallback``
    events; the hit/miss/bank/fallback counters always land in the
    metrics registry regardless.
    """

    def __init__(self, root: Optional[str] = None, *,
                 telemetry: Any = None, registry: Any = None):
        from ..utils.platform import default_aot_store_dir

        self.root = default_aot_store_dir(root)
        self.telemetry = telemetry
        if registry is None:
            if telemetry is not None and hasattr(telemetry, "registry"):
                registry = telemetry.registry
            else:
                from ..obs import default_registry

                registry = default_registry()
        self._hits = registry.counter(
            HITS_TOTAL, "AOT store hits (boot installed a stored "
            "executable; no trace, no compile)")
        self._misses = registry.counter(
            MISSES_TOTAL, "AOT store misses (normal trace+compile ran)")
        self._banks = registry.counter(
            BANKS_TOTAL, "executables serialized into the AOT store")
        self._fallbacks = registry.counter(
            FALLBACKS_TOTAL,
            "corrupt/incompatible entries quarantined (reason label)")

    # -- paths ---------------------------------------------------------------

    def _entry_paths(self, key: AotKey) -> Tuple[str, str]:
        d = os.path.join(self.root, key.name)
        return (os.path.join(d, f"{key.digest}.bin"),
                os.path.join(d, f"{key.digest}.json"))

    def _emit(self, kind: str, **fields: Any) -> None:
        if self.telemetry is None:
            return
        try:
            self.telemetry.emit(kind, **fields)
        except Exception:
            # The store must never take a boot down over telemetry.
            log.exception("aot %s event emission failed", kind)

    def _quarantine(self, key: AotKey, reason: str, detail: str) -> None:
        """Move the entry's files aside (``*.quarantined``) so the next
        boot re-banks a fresh entry instead of re-tripping on the same
        bad bytes, and the bad bytes stay inspectable."""
        for path in self._entry_paths(key):
            try:
                if os.path.exists(path):
                    os.replace(path, path + ".quarantined")
            except OSError:
                log.exception("aot quarantine of %s failed", path)
        self._fallbacks.inc(reason=reason)
        self._emit("aot_fallback", name=key.name, digest=key.digest,
                   reason=reason, detail=detail[:500])
        log.warning("aot entry %s/%s quarantined: %s (%s)", key.name,
                    key.digest[:12], reason, detail[:200])

    # Grace window for a half-written entry: put() renames the payload
    # and then the manifest; a reader seeing only one file younger than
    # this treats it as a bank in flight (plain miss), not corruption.
    _IN_FLIGHT_GRACE_S = 60.0

    def _in_flight(self, path: str) -> bool:
        try:
            return (time.time() - os.stat(path).st_mtime
                    < self._IN_FLIGHT_GRACE_S)
        except OSError:
            return True    # vanished underneath us: the writer/another
            #                reader is active — don't quarantine

    def contains(self, key: AotKey) -> bool:
        """Both entry files present — NO load, NO events/counters.
        Multi-program loaders (the LM prefill+decode pair) use this to
        decide all-or-nothing before any ``get``, so a partial entry
        set cannot mint a misleading ``aot_hit`` for a program the
        boot then compiles anyway."""
        bin_p, man_p = self._entry_paths(key)
        return os.path.exists(bin_p) and os.path.exists(man_p)

    # -- read ----------------------------------------------------------------

    def get(self, key: AotKey, *, in_tree: Any = None,
            out_tree: Any = None) -> Optional[Callable]:
        """Stored executable for ``key``, loaded — or None (plain miss
        OR quarantined-corrupt entry; either way the caller falls back
        to trace+compile and should re-bank with :meth:`put`).

        ``in_tree`` / ``out_tree`` (PyTreeDefs) override the trees
        stored in the entry — required for programs whose treedefs are
        not picklable (the train step's optax statics); the caller
        reconstructs them from exemplars.
        """
        bin_p, man_p = self._entry_paths(key)
        if not (os.path.exists(bin_p) and os.path.exists(man_p)):
            half = bin_p if os.path.exists(bin_p) else (
                man_p if os.path.exists(man_p) else None
            )
            if half is not None and not self._in_flight(half):
                # half an entry, and old enough that no writer is
                # plausibly between its two renames: a crashed bank or
                # a deleted file. A FRESH half is a concurrent put()
                # mid-bank (payload lands before manifest) — racing
                # replicas sharing one store must miss quietly, not
                # destroy each other's in-flight banks.
                self._quarantine(key, "incomplete_entry",
                                 "payload or manifest missing")
            else:
                self._misses.inc(name=key.name)
                self._emit("aot_miss", name=key.name, digest=key.digest)
            return None
        try:
            with open(man_p, "r", encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            self._quarantine(key, "corrupt_manifest",
                             f"{type(e).__name__}: {e}")
            return None
        try:
            with open(bin_p, "rb") as f:
                blob = f.read()
        except OSError as e:
            self._quarantine(key, "unreadable_payload",
                             f"{type(e).__name__}: {e}")
            return None
        if sha256_hex(blob) != manifest.get("payload_sha256"):
            self._quarantine(
                key, "payload_digest_mismatch",
                f"{len(blob)} bytes on disk do not hash to the "
                "manifest's payload_sha256 (truncated or tampered)")
            return None
        try:
            entry = pickle.loads(blob)
            payload = entry["payload"]
            stored_in, stored_out = entry["in_tree"], entry["out_tree"]
        except Exception as e:
            self._quarantine(key, "corrupt_payload",
                             f"{type(e).__name__}: {e}")
            return None
        in_tree = in_tree if in_tree is not None else stored_in
        out_tree = out_tree if out_tree is not None else stored_out
        if in_tree is None or out_tree is None:
            # Banked without picklable trees and the caller supplied
            # none: unusable as stored. Not a corruption — don't
            # quarantine, just miss (the caller knows its trees).
            self._misses.inc(name=key.name)
            self._emit("aot_miss", name=key.name, digest=key.digest,
                       reason="trees_required")
            return None
        try:
            from jax.experimental import serialize_executable as se

            loaded = se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:
            # The deserialize path is where jax/runtime incompatibility
            # actually surfaces (a payload built by another jax build or
            # for a missing device topology) — same fallback as corrupt.
            self._quarantine(key, "deserialize_error",
                             f"{type(e).__name__}: {e}")
            return None
        self._hits.inc(name=key.name)
        self._emit("aot_hit", name=key.name, digest=key.digest,
                   payload_bytes=len(blob))
        return loaded

    # -- write ---------------------------------------------------------------

    def put(self, key: AotKey, compiled: Any, *,
            meta: Optional[Dict[str, Any]] = None) -> bool:
        """Serialize ``compiled`` (a ``jax.stages.Compiled``) under
        ``key``. Returns False — never raises — when the backend cannot
        serialize executables or the write fails: banking is an
        optimization, and a bank failure must never take down the boot
        that just compiled successfully."""
        try:
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = se.serialize(compiled)
            try:
                trees: Tuple[Any, Any] = (in_tree, out_tree)
                pickle.dumps(trees)
            except Exception as e:
                # Unpicklable treedefs (optax statics in the train
                # step): store payload-only; get() then needs exemplar
                # trees from the caller.
                log.debug(
                    "aot %s: treedefs not picklable (%s: %s) — entry "
                    "stored payload-only, loads need exemplar trees",
                    key.name, type(e).__name__, e,
                )
                trees = (None, None)
            blob = pickle.dumps({
                "v": _SCHEMA_V, "payload": payload,
                "in_tree": trees[0], "out_tree": trees[1],
            })
            bin_p, man_p = self._entry_paths(key)
            os.makedirs(os.path.dirname(bin_p), exist_ok=True)
            manifest = {
                "v": _SCHEMA_V,
                "name": key.name,
                "digest": key.digest,
                "key": key.asdict(),
                "payload_sha256": sha256_hex(blob),
                "payload_bytes": len(blob),
                "trees_pickled": trees[0] is not None,
                "created_at": time.time(),
                "meta": meta or {},
            }
            for path, data in (
                (bin_p, blob),
                (man_p, json.dumps(manifest, indent=1).encode()),
            ):
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, path)
        except Exception as e:
            self._fallbacks.inc(reason="bank_failed")
            self._emit("aot_fallback", name=key.name, digest=key.digest,
                       reason="bank_failed",
                       detail=f"{type(e).__name__}: {e}"[:500])
            log.warning("aot bank of %s/%s failed: %s: %s", key.name,
                        key.digest[:12], type(e).__name__, e)
            return False
        self._banks.inc(name=key.name)
        self._emit("aot_bank", name=key.name, digest=key.digest,
                   payload_bytes=manifest["payload_bytes"])
        log.info("aot banked %s/%s (%d bytes)", key.name,
                 key.digest[:12], manifest["payload_bytes"])
        return True

    def load_or_compile(
        self, key: AotKey, build: Callable[[], Any], *,
        in_tree: Any = None, out_tree: Any = None,
        meta: Optional[Dict[str, Any]] = None,
    ) -> Tuple[Callable, str]:
        """The central path: hit → loaded executable; miss → ``build()``
        (which must return a ``Compiled``), re-bank, return it. Returns
        ``(executable, status)`` with status ``hit`` | ``miss``."""
        loaded = self.get(key, in_tree=in_tree, out_tree=out_tree)
        if loaded is not None:
            self._record_cost(key.name, loaded, "aot_hit")
            return loaded, "hit"
        compiled = build()
        self.put(key, compiled, meta=meta)
        self._record_cost(key.name, compiled, "aot_miss")
        return compiled, "miss"

    def _record_cost(self, name: str, compiled: Any, source: str) -> None:
        """Bank the executable's HLO costs in the per-program ledger
        (obs/costs; off-by-default, one attribute check). Both branches
        hold a real ``Compiled`` — the hit path's deserialized
        executable included — so the analysis performs no trace and no
        compile: the budget-0 boot fence stays green with costs armed."""
        from ..obs.costs import get_ledger

        ledger = get_ledger()
        if not ledger.enabled:
            return
        ledger.record(
            name, compiled, telemetry=self.telemetry, source=source,
        )

    # -- inventory (cli aot ls / gc) -----------------------------------------

    def entries(self) -> List[Dict[str, Any]]:
        """Manifest summaries of every entry (including quarantined and
        orphaned files, flagged as such) — the ``cli aot ls`` view."""
        out: List[Dict[str, Any]] = []
        if not os.path.isdir(self.root):
            return out
        for name in sorted(os.listdir(self.root)):
            d = os.path.join(self.root, name)
            if not os.path.isdir(d):
                continue
            files = sorted(os.listdir(d))
            manifests = {f[:-5] for f in files if f.endswith(".json")}
            payloads = {f[:-4] for f in files if f.endswith(".bin")}
            for digest in sorted(manifests | payloads):
                row: Dict[str, Any] = {
                    "name": name, "digest": digest,
                    "orphan": digest not in manifests
                    or digest not in payloads,
                }
                bin_p = os.path.join(d, f"{digest}.bin")
                if os.path.exists(bin_p):
                    st = os.stat(bin_p)
                    row["bytes"] = st.st_size
                    row["age_s"] = max(time.time() - st.st_mtime, 0.0)
                man_p = os.path.join(d, f"{digest}.json")
                if digest in manifests:
                    try:
                        with open(man_p, "r", encoding="utf-8") as f:
                            m = json.load(f)
                        row["key"] = m.get("key", {})
                        row["created_at"] = m.get("created_at")
                        row.setdefault("bytes", m.get("payload_bytes"))
                    except (OSError, ValueError):
                        row["orphan"] = True
                out.append(row)
            quarantined = [f for f in files if f.endswith(".quarantined")]
            if quarantined:
                out.append({
                    "name": name, "digest": None,
                    "quarantined": len(quarantined),
                })
        return out

    def gc(self, *, dry_run: bool = False) -> Dict[str, Any]:
        """Prune entries that can never hit again: code-rev mismatch
        against the CURRENT source tree (the store must not grow
        without bound across revisions), unknown program names,
        orphaned halves, and quarantined files. Entries for other
        jax versions/backends are also stale by construction — a
        different environment writes different digests — and are
        removed with reason ``environment``."""
        import jax

        from .programs import KNOWN_PROGRAMS, current_code_rev

        removed: List[Dict[str, str]] = []
        kept = 0
        if not os.path.isdir(self.root):
            return {"removed": removed, "kept": 0, "dry_run": dry_run}
        current = {n: current_code_rev(n) for n in KNOWN_PROGRAMS}
        for name in sorted(os.listdir(self.root)):
            d = os.path.join(self.root, name)
            if not os.path.isdir(d):
                continue
            # Decide per ENTRY (the manifest speaks for its payload),
            # so dry-run reports every file a real run would delete and
            # "kept" never counts a payload its manifest dooms.
            doomed: Dict[str, str] = {}   # fname -> reason
            for fname in sorted(os.listdir(d)):
                if fname.endswith(".quarantined") or fname.endswith(".tmp"):
                    doomed[fname] = "quarantined"
                elif fname.endswith(".bin"):
                    if not os.path.exists(
                        os.path.join(d, fname[:-4] + ".json")
                    ):
                        doomed[fname] = "orphan_payload"
                elif fname.endswith(".json"):
                    digest = fname[:-5]
                    reason = None
                    if not os.path.exists(os.path.join(d, digest + ".bin")):
                        reason = "orphan_manifest"
                    elif name not in current:
                        reason = "unknown_program"
                    else:
                        try:
                            with open(os.path.join(d, fname), "r",
                                      encoding="utf-8") as f:
                                key = json.load(f).get("key", {})
                        except (OSError, ValueError):
                            reason = "corrupt_manifest"
                        else:
                            if key.get("code_rev") != current[name]:
                                reason = "stale_code_rev"
                            elif (key.get("jax_version") != jax.__version__
                                  or key.get("backend")
                                  != jax.default_backend()):
                                reason = "environment"
                    if reason is not None:
                        doomed[fname] = reason
                        if reason != "orphan_manifest":
                            # a pruned manifest takes its payload along
                            doomed.setdefault(digest + ".bin", reason)
            for fname in sorted(os.listdir(d)):
                reason = doomed.get(fname)
                if reason is None:
                    kept += 1
                    continue
                removed.append({"name": name, "file": fname,
                                "reason": reason})
                if not dry_run:
                    try:
                        os.remove(os.path.join(d, fname))
                    except OSError:
                        log.exception("aot gc could not remove %s/%s",
                                      name, fname)
        return {"removed": removed, "kept": kept, "dry_run": dry_run}
