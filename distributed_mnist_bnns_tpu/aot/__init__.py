"""Ahead-of-time compiled-executable store — zero-compile cold starts.

``cli aot build`` compiles the known jit signatures (classifier
predict, LM chunked-prefill + decode, the train step) into a
content-addressed on-disk store; trainer and server boots consult the
store first and install executables instead of tracing (PERF.md "Cold
start", ``aot_hit``/``aot_miss``/``aot_bank``/``aot_fallback`` events
in OBSERVABILITY.md). With the store warm, both serving engines boot
with ZERO XLA compiles and the recompile fence (analysis/guards.py)
enforces budget 0 from boot.
"""

from .store import (  # noqa: F401
    AotKey,
    AotStore,
    canonical_extra,
    format_avals,
    make_key,
    sha256_hex,
)
from .programs import (  # noqa: F401
    KNOWN_PROGRAMS,
    current_code_rev,
    load_or_compile_train_step,
    load_packed_aot,
    load_paged_lm_decoder_aot,
)
