"""The known AOT program signatures and their store loaders.

Five programs cover every hot entry point's first dispatch (PERF.md
"Cold start"):

  * ``classifier_predict`` — the packed classifier at the server's ONE
    compiled micro-batch shape (serve/core.py's whole contract);
  * ``lm_prefill`` / ``lm_decode`` — the continuous-batching engine's
    exactly-two programs (infer_transformer.make_paged_lm_decoder);
  * ``lm_verify`` — the engine's THIRD program when speculative
    decoding is armed (``spec_k > 0``): the fixed-K dense-bf16 verify
    dispatch. An ``--aot --spec-decode K`` boot extends the LM pair's
    all-or-nothing discipline to the triple — any absent member is a
    miss for all of them;
  * ``train_step`` — the single-device jitted train step (the mesh
    dispatches re-lower per topology and stay on the online path).

Each loader owns the full key construction for its program — the same
function serves ``cli aot build`` (bank), server boot (hit → install)
and hot reload, so the key schema cannot drift between writer and
reader.

Code revision: each program hashes the source files that define its
traced computation (``_REV_MODULES``). Conservative by design — an
edit to any listed module invalidates the program's entries even if
the traced math is unchanged; a stale executable silently serving old
code would be far worse, and ``cli aot gc`` prunes the casualties.

The executables embed their closure constants (the artifact's packed
weights, folded BN thresholds, LM embeddings), which is why every
artifact-derived key carries the artifact file's sha256 in ``consts``:
same shapes + different weights MUST miss.

**Donation is disabled in every AOT program.** On jaxlib 0.4.37 (CPU
PJRT) a deserialized executable with input-output aliasing double-
frees the donated buffers — measured as nondeterministic glibc heap
corruption ("corrupted double-linked list" / segfault, ~30% of runs)
in the chained prefill→decode pools case. The online-jit paths keep
their donation; the AOT variants pay one extra transient copy of the
donated operand (KV pools / train state) per dispatch instead.
``JG_AOT_DONATE=1`` re-enables donation for backends where the
aliasing round-trips safely — it is part of the key, so flipping it
cannot alias into the wrong entry.
"""

from __future__ import annotations

import hashlib
import importlib.util
import logging
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .store import AotKey, AotStore, format_avals, make_key, sha256_hex

log = logging.getLogger(__name__)

_PKG = __package__.rsplit(".", 1)[0]  # distributed_mnist_bnns_tpu

# Source modules whose text defines each program's traced computation.
# Hashing FILES (not live objects) keeps this import-light and makes
# the revision a pure function of the checked-out tree — what "matches
# HEAD" means for `cli aot gc`.
_REV_MODULES: Dict[str, Tuple[str, ...]] = {
    "classifier_predict": (
        f"{_PKG}.infer", f"{_PKG}.infer_conv", f"{_PKG}.infer_moe",
        f"{_PKG}.infer_qnn", f"{_PKG}.infer_transformer",
        f"{_PKG}.ops.binarize", f"{_PKG}.ops.bitpack",
        f"{_PKG}.ops.xnor_gemm",
    ),
    # ops.flash_attention rides along: paged_kv's Pallas kernels import
    # their online-softmax constants from it, so an edit there changes
    # the traced attention math of all three LM programs.
    "lm_prefill": (
        f"{_PKG}.infer_transformer", f"{_PKG}.ops.paged_kv",
        f"{_PKG}.ops.binarize", f"{_PKG}.ops.bitpack",
        f"{_PKG}.ops.xnor_gemm", f"{_PKG}.ops.flash_attention",
    ),
    "lm_decode": (
        f"{_PKG}.infer_transformer", f"{_PKG}.ops.paged_kv",
        f"{_PKG}.ops.binarize", f"{_PKG}.ops.bitpack",
        f"{_PKG}.ops.xnor_gemm", f"{_PKG}.ops.flash_attention",
    ),
    "lm_verify": (
        f"{_PKG}.infer_transformer", f"{_PKG}.ops.paged_kv",
        f"{_PKG}.ops.binarize", f"{_PKG}.ops.bitpack",
        f"{_PKG}.ops.xnor_gemm", f"{_PKG}.ops.flash_attention",
    ),
    "train_step": (
        f"{_PKG}.train.trainer", f"{_PKG}.train.optim",
        f"{_PKG}.ops.losses", f"{_PKG}.ops.binarize",
        f"{_PKG}.ops.augment", f"{_PKG}.ops.bitpack",
        f"{_PKG}.ops.xnor_gemm",
        f"{_PKG}.models.registry", f"{_PKG}.models.layers",
        f"{_PKG}.models.mlp", f"{_PKG}.models.cnn",
        f"{_PKG}.models.bnn_cnn", f"{_PKG}.models.convnet",
        f"{_PKG}.models.resnet", f"{_PKG}.models.transformer",
        f"{_PKG}.models.moe",
    ),
}

KNOWN_PROGRAMS = tuple(_REV_MODULES)

_rev_cache: Dict[str, str] = {}


def aot_donate() -> bool:
    """Donation for AOT-compiled programs (module docstring): off by
    default — jaxlib 0.4.37's deserialized executables double-free
    aliased buffers; ``JG_AOT_DONATE=1`` opts back in elsewhere."""
    import os

    return os.environ.get("JG_AOT_DONATE", "") == "1"


def current_code_rev(name: str) -> str:
    """sha256 over the source bytes of the program's ``_REV_MODULES``
    (plus the aot package itself — a store-format change must also
    invalidate)."""
    if name in _rev_cache:
        return _rev_cache[name]
    if name not in _REV_MODULES:
        raise KeyError(
            f"unknown AOT program {name!r} (have: {KNOWN_PROGRAMS})"
        )
    h = hashlib.sha256()
    for mod in _REV_MODULES[name] + (f"{_PKG}.aot.store",):
        spec = importlib.util.find_spec(mod)
        if spec is None or not spec.origin:
            raise RuntimeError(f"cannot locate source of module {mod}")
        with open(spec.origin, "rb") as f:
            h.update(f.read())
        h.update(b"\x00")
    _rev_cache[name] = h.hexdigest()
    return _rev_cache[name]


def _read_artifact(path: str) -> Tuple[Dict[str, Any], str]:
    """(frozen dict, sha256 of the file bytes) — the bytes digest is
    the ``consts`` key component: the executable embeds the weights."""
    from flax import serialization

    with open(path, "rb") as f:
        raw = f.read()
    return serialization.msgpack_restore(raw), sha256_hex(raw)


# ---------------------------------------------------------------------------
# classifier predict
# ---------------------------------------------------------------------------


def classifier_predict_key(
    artifact_digest: str, *, batch_size: int, input_shape, interpret: bool,
    family: str = "",
) -> AotKey:
    import jax
    import jax.numpy as jnp

    sds = jax.ShapeDtypeStruct(
        (int(batch_size), *[int(d) for d in input_shape]), jnp.float32
    )
    return make_key(
        "classifier_predict",
        avals=format_avals(sds),
        consts=artifact_digest,
        extra={"interpret": bool(interpret), "family": family},
    )


def load_packed_aot(
    path: str, *, batch_size: int, input_shape, interpret: bool,
    store: AotStore,
):
    """AOT-aware ``infer.load_packed`` at ONE batch shape.

    Returns ``(predict_fn, info, aot_meta)``. On a hit the predict fn
    is the deserialized executable — the artifact's weights never touch
    the device as arrays (they are baked into the program), no apply fn
    is built, nothing traces or compiles. On a miss the normal builder
    runs, is explicitly lowered+compiled at the batch shape, banked,
    and the ``Compiled`` is returned (so hit and miss serve through the
    same strict-shape call convention: the micro-batcher always pads to
    exactly this shape).
    """
    import jax
    import jax.numpy as jnp

    frozen, digest = _read_artifact(path)
    info = dict(frozen["info"])
    key = classifier_predict_key(
        digest, batch_size=batch_size, input_shape=input_shape,
        interpret=interpret, family=str(info.get("family", "")),
    )

    def build():
        from ..infer import _build_any

        fn = _build_any(frozen, interpret)
        sds = jax.ShapeDtypeStruct(
            (int(batch_size), *[int(d) for d in input_shape]),
            jnp.float32,
        )
        return fn.lower(sds).compile()

    predict_fn, status = store.load_or_compile(
        key, build,
        meta={"artifact": path, "family": info.get("family")},
    )
    return predict_fn, info, {"status": status, "digest": key.digest}


# ---------------------------------------------------------------------------
# paged LM decoder (prefill + decode)
# ---------------------------------------------------------------------------


def _lm_geometry(
    frozen: Dict[str, Any], *, slots: int, page_size: int,
    num_pages: Optional[int], prefill_chunk: int, max_len: Optional[int],
    spec_k: int = 0,
) -> Dict[str, int]:
    """Host-side mirror of ``make_paged_lm_decoder``'s geometry math
    (validated against the real decoder on every miss, so drift cannot
    ship silently). Needed so a HIT can build pools and page tables
    without constructing — i.e. without tracing — the decoder."""
    from ..ops.paged_kv import pages_needed

    if frozen.get("kind") != "lm":
        raise ValueError(
            f"make_paged_lm_decoder needs a kind='lm' artifact, got "
            f"{frozen.get('kind')!r}"
        )
    num_heads = int(frozen["num_heads"])
    embed_dim = int(np.asarray(frozen["tok_embed"]).shape[1])
    vocab = int(np.asarray(frozen["tok_embed"]).shape[0])
    pos_len = int(np.asarray(frozen["pos_embed"]).shape[1])
    n_blocks = len(frozen["blocks"])
    max_len = pos_len if max_len is None else int(max_len)
    if not 1 <= max_len <= pos_len:
        raise ValueError(
            f"max_len {max_len} outside [1, trained pos_embed length "
            f"{pos_len}]"
        )
    slots = int(slots)
    if slots < 1:
        raise ValueError(f"need >= 1 batch slot, got {slots}")
    page_size = int(page_size)
    prefill_chunk = int(prefill_chunk)
    max_pages = pages_needed(max_len, page_size)
    if num_pages is None:
        num_pages = slots * max_pages + 1
    spec_k = int(spec_k)
    if spec_k < 0:
        raise ValueError(f"spec_k must be >= 0, got {spec_k}")
    return {
        "slots": slots, "page_size": page_size,
        "num_pages": int(num_pages), "max_pages": max_pages,
        "max_len": max_len, "prefill_chunk": prefill_chunk,
        "vocab": vocab, "num_blocks": n_blocks,
        "num_heads": num_heads, "head_dim": embed_dim // num_heads,
        "spec_k": spec_k,
    }


def _lm_avals(geom: Dict[str, int]):
    """(pools, prefill-args, decode-args, verify-args-or-None)
    ShapeDtypeStruct trees for the programs' fixed signatures."""
    import jax
    import jax.numpy as jnp

    pool = jax.ShapeDtypeStruct(
        (geom["num_pages"], geom["page_size"], geom["num_heads"],
         geom["head_dim"]), jnp.float32,
    )
    pools = tuple((pool, pool) for _ in range(geom["num_blocks"]))
    i32 = jnp.int32
    s = jax.ShapeDtypeStruct
    prefill = (pools, s((geom["prefill_chunk"],), i32),
               s((geom["max_pages"],), i32), s((), i32), s((), i32))
    decode = (pools, s((geom["slots"],), i32),
              s((geom["slots"], geom["max_pages"]), i32),
              s((geom["slots"],), i32))
    verify = None
    if geom.get("spec_k"):
        verify = (pools, s((geom["slots"], geom["spec_k"]), i32),
                  s((geom["slots"], geom["max_pages"]), i32),
                  s((geom["slots"],), i32))
    return pools, prefill, decode, verify


def lm_decoder_keys(
    artifact_digest: str, geom: Dict[str, int], *, interpret: bool,
    kernels: bool = False,
) -> Tuple[AotKey, AotKey, Optional[AotKey]]:
    """(prefill, decode, verify-or-None) keys. ``spec_k`` shapes ONLY
    the verify key: the prefill/decode programs are identical with
    spec decode on or off, so the pair banked by a plain boot serves a
    spec-armed boot too — which still misses as a set until
    ``lm_verify`` is banked (the all-or-nothing discipline).
    ``kernels`` keys all three: the Pallas paged-attention +
    fused-unpack programs are different executables from the gather
    path, so flipping the flag must miss."""
    _, prefill_avals, decode_avals, verify_avals = _lm_avals(geom)
    extra = {k: v for k, v in geom.items() if k != "spec_k"}
    extra.update(
        interpret=bool(interpret), donate=aot_donate(),
        kernels=bool(kernels),
    )
    key_v = None
    if verify_avals is not None:
        key_v = make_key(
            "lm_verify", avals=format_avals(verify_avals),
            consts=artifact_digest,
            extra={**extra, "spec_k": geom["spec_k"]},
        )
    return (
        make_key("lm_prefill", avals=format_avals(prefill_avals),
                 consts=artifact_digest, extra=extra),
        make_key("lm_decode", avals=format_avals(decode_avals),
                 consts=artifact_digest, extra=extra),
        key_v,
    )


def load_paged_lm_decoder_aot(
    path: str, *, slots: int, page_size: int = 16,
    num_pages: Optional[int] = None, prefill_chunk: int = 16,
    max_len: Optional[int] = None, spec_k: int = 0,
    interpret: bool = False, kernels: bool = False, store: AotStore,
):
    """AOT-aware ``make_paged_lm_decoder`` from an artifact file.

    Returns ``(PagedLMDecoder, info, aot_meta)``. Hit (EVERY program
    present — the prefill/decode pair, plus ``lm_verify`` when
    ``spec_k > 0``): the decoder's programs are deserialized
    executables and ``init_pools`` builds the KV pools via
    ``device_put`` of host zeros — the whole load performs **zero**
    XLA compiles, which is what lets the engine's recompile fence pin
    its budget-0 baseline at BOOT instead of post-warmup. Miss: the
    real decoder is built, every program is explicitly lowered +
    compiled (donation preserved), banked, and returned as
    ``Compiled``s.
    """
    import jax

    from ..infer_transformer import PagedLMDecoder

    frozen, digest = _read_artifact(path)
    info = dict(frozen.get("info", {}))
    geom = _lm_geometry(
        frozen, slots=slots, page_size=page_size, num_pages=num_pages,
        prefill_chunk=prefill_chunk, max_len=max_len, spec_k=spec_k,
    )
    key_p, key_d, key_v = lm_decoder_keys(
        digest, geom, interpret=interpret, kernels=kernels
    )
    keys = [key_p, key_d] + ([key_v] if key_v is not None else [])
    # All-or-nothing: only touch get() (which emits hit/miss events and
    # counters) when EVERY program is present — a partially-present set
    # is a miss for the whole set, and must not record an aot_hit for a
    # program this boot then compiles anyway. With spec decode armed
    # the pair-miss discipline extends to the triple.
    loaded: list = []
    if all(store.contains(k) for k in keys):
        for k in keys:
            exe = store.get(k)
            if exe is None:
                loaded = []
                break
            loaded.append(exe)

    pool_shape = (geom["num_pages"], geom["page_size"],
                  geom["num_heads"], geom["head_dim"])

    def init_pools_host():
        # device_put of host zeros: no broadcast program, no compile —
        # distinct buffers per pool (the programs donate the pytree and
        # XLA rejects donating one buffer twice).
        return tuple(
            (jax.device_put(np.zeros(pool_shape, np.float32)),
             jax.device_put(np.zeros(pool_shape, np.float32)))
            for _ in range(geom["num_blocks"])
        )

    if len(loaded) == len(keys):
        decoder = PagedLMDecoder(
            init_pools=init_pools_host,
            prefill=loaded[0],
            decode=loaded[1],
            slots=geom["slots"], page_size=geom["page_size"],
            num_pages=geom["num_pages"], max_pages=geom["max_pages"],
            max_len=geom["max_len"], prefill_chunk=geom["prefill_chunk"],
            vocab=geom["vocab"], num_blocks=geom["num_blocks"],
            verify=loaded[2] if key_v is not None else None,
            spec_k=geom["spec_k"],
            kernels=bool(kernels),
        )
        return decoder, info, {
            "status": "hit",
            "digests": [k.digest for k in keys],
        }

    # miss (or a partial set): build the real decoder, compile + bank
    from ..infer_transformer import make_paged_lm_decoder

    dec = make_paged_lm_decoder(
        frozen, slots=slots, page_size=page_size, num_pages=num_pages,
        prefill_chunk=prefill_chunk, max_len=max_len, spec_k=spec_k,
        interpret=interpret, kernels=kernels,
        donate=aot_donate(),   # see module docstring: donation +
                               # deserialize double-frees on 0.4.37
    )
    derived = (geom["slots"], geom["page_size"], geom["num_pages"],
               geom["max_pages"], geom["max_len"],
               geom["prefill_chunk"], geom["vocab"],
               geom["num_blocks"], geom["spec_k"])
    actual = (dec.slots, dec.page_size, dec.num_pages, dec.max_pages,
              dec.max_len, dec.prefill_chunk, dec.vocab,
              dec.num_blocks, dec.spec_k)
    if derived != actual:
        raise RuntimeError(
            f"aot LM geometry drifted from make_paged_lm_decoder: "
            f"derived {derived} != actual {actual} — fix "
            f"aot/programs._lm_geometry"
        )
    _, prefill_avals, decode_avals, verify_avals = _lm_avals(geom)
    comp_p = dec.prefill.lower(*prefill_avals).compile()
    comp_d = dec.decode.lower(*decode_avals).compile()
    meta = {"artifact": path, "kernels": bool(kernels), **geom}
    store.put(key_p, comp_p, meta=meta)
    store.put(key_d, comp_d, meta=meta)
    comp_v = None
    if key_v is not None:
        comp_v = dec.verify.lower(*verify_avals).compile()
        store.put(key_v, comp_v, meta=meta)
    decoder = dec._replace(
        init_pools=init_pools_host, prefill=comp_p, decode=comp_d,
        verify=comp_v,
    )
    return decoder, info, {
        "status": "miss", "digests": [k.digest for k in keys],
    }


# ---------------------------------------------------------------------------
# train step
# ---------------------------------------------------------------------------


def load_or_compile_train_step(
    store: AotStore, *, jitted_step, state, images_aval, labels_aval,
    rng, extra: Dict[str, Any],
):
    """AOT load/bank for the single-device jitted train step.

    The step's pytree defs are NOT picklable (optax transforms in
    ``TrainState.tx`` hold closures), so the entry stores the payload
    only and the trees are reconstructed here from exemplars — the
    caller's live ``state`` and input avals, which by construction
    match the signature the executable was compiled for (the key's
    avals field proves it).

    Returns ``(step_callable, status)`` — the callable is strict about
    shapes (``Compiled``); the Trainer keeps the online-jit step as a
    fallback for trailing partial batches.
    """
    import jax

    key = make_key(
        "train_step",
        avals=format_avals((state, images_aval, labels_aval, rng)),
        extra=extra,
    )
    in_tree = jax.tree_util.tree_structure(
        ((state, images_aval, labels_aval, rng), {})
    )
    metric = jax.ShapeDtypeStruct((), jax.numpy.float32)
    out_tree = jax.tree_util.tree_structure(
        (state, {"loss": metric, "accuracy": metric})
    )

    def build():
        return jitted_step.lower(
            state, images_aval, labels_aval, rng
        ).compile()

    return store.load_or_compile(
        key, build, in_tree=in_tree, out_tree=out_tree,
        meta={"model": extra.get("model")},
    )
