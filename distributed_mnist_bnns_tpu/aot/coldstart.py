"""Cold-start measurement worker — one boot, timed, as JSON.

``bench.py --cold-start-bench`` runs this module as a SUBPROCESS (a
cold start measured in a warm process is a lie: in-process jit caches,
imported modules and a live backend hide exactly the cost being
measured), twice per target: once against an empty store (cold — the
run banks its executables on the way) and once against the store the
first run just filled (warm). Each subprocess gets a FRESH jax
persistent compilation cache dir, so the comparison isolates the AOT
store's contribution over the full trace+lower+compile pipeline, not
just the XLA half ``.jax_cache`` already skips.

Three modes mirror the three boot paths:

  serve   boot PackedInferenceServer, time to ready and to the first
          /predict-equivalent response (time-to-first-token for a
          classifier IS its first response)
  lm      boot LMServer, time to ready and to the FIRST streamed token
          of a generation request
  train   construct the Trainer (includes the AOT step install), time
          to the first completed optimizer step

Output: one JSON line on stdout::

  {"mode": ..., "aot": ..., "aot_status": hit|miss|disabled,
   "boot_s": <entry -> server/trainer ready>,
   "first_s": <entry -> first token/response/step complete>,
   "compiles": <backend compiles observed in this process>}

``boot_s``/``first_s`` count from module entry (after interpreter +
import startup, which is identical in both runs and would otherwise
drown the signal in noise); the parent additionally records the wall
time of the whole subprocess.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

_T0 = time.perf_counter()


def _elapsed() -> float:
    return time.perf_counter() - _T0


def make_tiny_artifacts(
    work: str, *, lm_vocab: int = 32, lm_max_len: int = 32,
    lm_embed: int = 32, seed: int = 0,
):
    """Export the tiny classifier + LM artifacts the cold-start bench
    and the aot smoke boot from (untrained — cold-start cost is
    weight-value-independent). ONE definition for both callers, so an
    artifact-format change cannot drift between them. Returns
    ``(classifier_path, lm_path)``."""
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np
    from flax import serialization

    from ..infer import export_packed
    from ..infer_transformer import _freeze_lm_tensors
    from ..models import bnn_mlp_small
    from ..models.transformer import BinarizedLM

    root = jax.random.PRNGKey(seed)
    cls_path = os.path.join(work, "cls.msgpack")
    model = bnn_mlp_small(backend="xla")
    x = jax.random.normal(jax.random.fold_in(root, 0), (8, 28, 28, 1))
    variables = model.init(
        {"params": jax.random.fold_in(root, 1),
         "dropout": jax.random.fold_in(root, 2)}, x, train=True,
    )
    export_packed(model, variables, cls_path)

    lm_path = os.path.join(work, "lm.msgpack")
    lm = BinarizedLM(
        vocab=lm_vocab, max_len=lm_max_len, embed_dim=lm_embed,
        depth=2, num_heads=2, attention="xla", backend="xla",
    )
    lv = lm.init({"params": jax.random.fold_in(root, 3)},
                 jnp.zeros((1, 8), jnp.int32))
    frozen = jax.tree.map(
        lambda v: np.asarray(v) if hasattr(v, "shape") else v,
        _freeze_lm_tensors(lm, lv),
    )
    with open(lm_path, "wb") as f:
        f.write(serialization.msgpack_serialize(frozen))
    return cls_path, lm_path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--mode", required=True,
                   choices=["serve", "lm", "train"])
    p.add_argument("--artifact", default=None,
                   help="packed artifact (serve/lm modes)")
    p.add_argument("--store", required=True, help="AOT store root")
    p.add_argument("--no-aot", action="store_true",
                   help="measure the fully-online baseline instead")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--slots", type=int, default=2)
    p.add_argument("--page-size", type=int, default=8)
    p.add_argument("--model", default="bnn-mlp-small")
    p.add_argument("--train-batch-size", type=int, default=32)
    args = p.parse_args(argv)
    aot = not args.no_aot

    import numpy as np

    from ..obs import get_tracker

    tracker = get_tracker()
    out = {"mode": args.mode, "aot": aot}

    if args.mode == "serve":
        from ..serve import PackedInferenceServer, ServeConfig

        srv = PackedInferenceServer(ServeConfig(
            artifact=args.artifact, port=0, batch_size=args.batch_size,
            interpret=True, aot=aot, aot_dir=args.store,
        ))
        srv.start()
        out["boot_s"] = _elapsed()
        req = srv.engine.submit(
            np.zeros((1, 28, 28, 1), np.float32),
            deadline=time.monotonic() + 60,
        )
        if isinstance(req, str) or not req.event.wait(60):
            print(json.dumps({**out, "error": f"no response ({req})"}))
            return 1
        out["first_s"] = _elapsed()
        out["aot_status"] = srv.aot_status
        srv.request_stop()
        srv.drain_and_stop()

    elif args.mode == "lm":
        from ..serve.lm import LMServeConfig, LMServer

        srv = LMServer(LMServeConfig(
            artifact=args.artifact, port=0, slots=args.slots,
            page_size=args.page_size, interpret=True,
            aot=aot, aot_dir=args.store,
        ))
        srv.start()
        out["boot_s"] = _elapsed()
        req = srv.engine.submit(
            np.array([1, 2, 3], np.int32), 4,
            time.monotonic() + 60,
        )
        if isinstance(req, str):
            print(json.dumps({**out, "error": f"shed: {req}"}))
            return 1
        first = req.events.get(timeout=60)
        out["first_s"] = _elapsed()
        out["first_kind"] = first.get("kind")
        out["aot_status"] = srv.aot_status
        while first.get("kind") != "done":
            first = req.events.get(timeout=60)
        srv.request_stop()
        srv.drain_and_stop()

    else:  # train
        import jax.numpy as jnp

        from ..train import TrainConfig, Trainer

        trainer = Trainer(TrainConfig(
            model=args.model, batch_size=args.train_batch_size,
            epochs=1, log_interval=10 ** 9,
            aot=aot, aot_dir=args.store,
        ))
        out["boot_s"] = _elapsed()
        rng = np.random.RandomState(0)
        images = jnp.asarray(
            rng.rand(args.train_batch_size, 28, 28, 1).astype(np.float32)
        )
        labels = jnp.asarray(
            (np.arange(args.train_batch_size) % 10).astype(np.int32)
        )
        state, metrics = trainer.train_step(
            trainer.state, images, labels, trainer.rng
        )
        import jax

        jax.block_until_ready(metrics["loss"])
        out["first_s"] = _elapsed()
        out["aot_status"] = trainer.aot_status

    out["compiles"] = tracker.count
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
