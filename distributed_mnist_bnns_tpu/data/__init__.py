from .cifar import CIFAR10_MEAN, CIFAR10_STD, load_cifar10
from .common import ImageClassData, prefetch_to_device
from .imagenet import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    ImageNetStream,
    load_imagenet,
    open_imagenet_stream,
)
from .mnist import (
    MnistData,
    load_idx,
    load_mnist,
    shard_indices,
    batch_iterator,
    native_batch_iterator,
    MNIST_MEAN,
    MNIST_STD,
)


def load_dataset(name: str, data_dir=None, **kwargs) -> ImageClassData:
    """Dispatch to a dataset pipeline by name
    ("mnist" | "cifar10" | "imagenet")."""
    if name == "mnist":
        return load_mnist(data_dir, **kwargs)
    if name in ("cifar10", "cifar"):
        return load_cifar10(data_dir, **kwargs)
    if name == "imagenet":
        return load_imagenet(data_dir, **kwargs)
    raise ValueError(
        f"unknown dataset {name!r} (have: mnist, cifar10, imagenet)"
    )


__all__ = [
    "ImageClassData",
    "prefetch_to_device",
    "MnistData",
    "load_idx",
    "load_mnist",
    "load_cifar10",
    "load_imagenet",
    "open_imagenet_stream",
    "ImageNetStream",
    "load_dataset",
    "shard_indices",
    "batch_iterator",
    "native_batch_iterator",
    "MNIST_MEAN",
    "MNIST_STD",
    "CIFAR10_MEAN",
    "CIFAR10_STD",
    "IMAGENET_MEAN",
    "IMAGENET_STD",
]
