from .mnist import (
    MnistData,
    load_idx,
    load_mnist,
    shard_indices,
    batch_iterator,
    MNIST_MEAN,
    MNIST_STD,
)

__all__ = [
    "MnistData",
    "load_idx",
    "load_mnist",
    "shard_indices",
    "batch_iterator",
    "MNIST_MEAN",
    "MNIST_STD",
]
