"""Shared dataset container used by every data pipeline (mnist, cifar).

The trainer and parallel wrappers duck-type against these four arrays, so
any image-classification dataset can plug in by returning this class.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class ImageClassData:
    """Train/test images as normalized float32 NHWC, int32 labels."""

    train_images: np.ndarray
    train_labels: np.ndarray
    test_images: np.ndarray
    test_labels: np.ndarray
    source: str = ""        # e.g. "mnist" | "t10k-split" | "synthetic"
    name: str = "mnist"     # dataset family
    n_classes: int = 10     # label-space size (imagenet: up to 1000)

    @property
    def input_shape(self):
        return tuple(self.train_images.shape[1:])


def normalize_u8(
    images_u8: np.ndarray,
    norm: str,
    *,
    stats_name: str,
    mean,
    std,
) -> np.ndarray:
    """uint8 images -> float32 in [0,1], then normalized.

    ``norm`` is the dataset's own stats name (e.g. "mnist" / "cifar"),
    "half" ((x-0.5)/0.5 — the reference's mnist-distributed-BNNS2.py:82
    variant), or "none"."""
    x = images_u8.astype(np.float32) / 255.0
    if norm == stats_name:
        x = (x - mean) / std
    elif norm == "half":
        x = (x - 0.5) / 0.5
    elif norm != "none":
        raise ValueError(
            f"unknown norm {norm!r} (have: {stats_name!r}, 'half', 'none')"
        )
    return x


def synthetic_blobs(
    image_shape, n_train: int, n_test: int, seed: int, n_classes: int = 10
):
    """Class-conditional blobs: each class gets a fixed random template;
    samples are template + noise. Linearly separable enough for convergence
    tests while shaped exactly like the real dataset. Returns uint8
    (train_x, train_y, test_x, test_y)."""
    rng = np.random.RandomState(seed)
    templates = rng.rand(n_classes, *image_shape).astype(np.float32)

    def make(n):
        labels = rng.randint(0, n_classes, size=n).astype(np.int32)
        imgs = templates[labels] + 0.3 * rng.randn(n, *image_shape).astype(
            np.float32
        )
        return (np.clip(imgs, 0.0, 1.0) * 255).astype(np.uint8), labels

    tr_x, tr_y = make(n_train)
    te_x, te_y = make(n_test)
    return tr_x, tr_y, te_x, te_y


def prefetch_to_device(iterator, size: int = 2, sharding=None):
    """Wrap a host batch iterator so device transfers run ahead of compute.

    Keeps ``size`` batches in flight: each is jax.device_put (optionally
    with a Sharding for distributed layouts) as soon as a slot frees, so the
    H2D copy of batch k+1 overlaps the computation of batch k — the role
    torch DataLoader's pin_memory/non_blocking copy plays in the reference's
    hot loop (mnist-dist2.py:119-120), done JAX-natively. device_put is
    async; the queue just bounds how far the host runs ahead.
    """
    import collections

    import jax

    queue = collections.deque()

    def put(batch):
        if sharding is None:
            return jax.tree.map(jax.device_put, batch)
        return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)

    for batch in iterator:
        queue.append(put(batch))
        if len(queue) >= size:
            yield queue.popleft()
    while queue:
        yield queue.popleft()
