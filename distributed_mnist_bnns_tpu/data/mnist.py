"""MNIST data pipeline: idx parsing, normalization, deterministic per-host
sharding, and batching.

Replaces the reference's torchvision.datasets.MNIST + DataLoader +
DistributedSampler stack (mnist-dist2.py:96-108) with a numpy/JAX-native
pipeline:

  * idx ubyte files (optionally gzipped) are parsed directly — the same
    on-disk layout torchvision produces under data/MNIST/raw;
  * normalization matches the reference transforms: (0.1307, 0.3081) in most
    scripts, (0.5, 0.5) in mnist-distributed-BNNS2.py:82 ("half" variant);
  * ``shard_indices`` reproduces DistributedSampler semantics — a
    deterministic epoch-seeded permutation, padded to a multiple of the
    world size, strided by rank (mnist-dist2.py:100-102) — implemented
    host-side so each JAX process feeds only its own shard;
  * a synthetic fallback keeps every code path runnable when the real blobs
    are absent (this workspace ships only the t10k images; see
    reference .MISSING_LARGE_BLOBS).

The C++ fast loader (native/) plugs in underneath ``load_idx`` when built;
the pure-numpy path is always available.
"""

from __future__ import annotations

import gzip
import logging
import os
import struct
from typing import Iterator, Tuple

import numpy as np

from .common import ImageClassData, normalize_u8, synthetic_blobs

log = logging.getLogger(__name__)

MNIST_MEAN = 0.1307
MNIST_STD = 0.3081

_DEFAULT_DIRS = (
    os.path.join(os.path.dirname(__file__), "..", "..", "data", "MNIST", "raw"),
    "/root/reference/data/MNIST/raw",
    "./data/MNIST/raw",
)


def load_idx(path: str) -> np.ndarray:
    """Parse an idx ubyte file (magic 0x0801 labels / 0x0803 images), gz ok.

    Uses the native C++ decoder (native/) for raw files when built; falls
    back to the pure-python parser (always used for .gz)."""
    if not path.endswith(".gz"):
        try:
            from .. import native

            arr = native.load_idx_native(path)
            if arr is not None:
                return arr
        except Exception as e:  # pragma: no cover - fall through to python
            log.debug("native idx decode failed (%s); python parser", e)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        if (magic >> 8) != 0x08 or ndim not in (1, 3):
            raise ValueError(f"{path}: bad idx magic {magic:#x}")
        dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _find_file(data_dir: str, stem: str) -> str | None:
    for suffix in ("", ".gz"):
        p = os.path.join(data_dir, stem + suffix)
        if os.path.exists(p):
            return p
    return None


# Backwards-compatible name: MNIST returns the shared dataset container.
MnistData = ImageClassData


def _normalize(images_u8: np.ndarray, norm: str) -> np.ndarray:
    x = normalize_u8(
        images_u8, norm, stats_name="mnist", mean=MNIST_MEAN, std=MNIST_STD
    )
    return x[..., None]  # NHWC with 1 channel


def _synthetic(n_train: int, n_test: int, seed: int) -> Tuple[np.ndarray, ...]:
    return synthetic_blobs((28, 28), n_train, n_test, seed)


def load_mnist(
    data_dir: str | None = None,
    *,
    norm: str = "mnist",
    synthetic_ok: bool = True,
    synthetic_sizes: Tuple[int, int] = (60000, 10000),
    seed: int = 0,
) -> MnistData:
    """Load MNIST with graceful degradation.

    Resolution order:
      1. full train + t10k idx files under ``data_dir`` (or the first
         default dir that has them);
      2. t10k only -> deterministic 9k/1k train/test split of the 10k set;
      3. synthetic class-conditional data (if ``synthetic_ok``).
    """
    dirs = [data_dir] if data_dir else [d for d in _DEFAULT_DIRS]
    for d in dirs:
        if d is None or not os.path.isdir(d):
            continue
        tr_x_p = _find_file(d, "train-images-idx3-ubyte")
        tr_y_p = _find_file(d, "train-labels-idx1-ubyte")
        te_x_p = _find_file(d, "t10k-images-idx3-ubyte")
        te_y_p = _find_file(d, "t10k-labels-idx1-ubyte")
        if te_x_p and te_y_p:
            te_x, te_y = load_idx(te_x_p), load_idx(te_y_p).astype(np.int32)
            if tr_x_p and tr_y_p:
                tr_x, tr_y = load_idx(tr_x_p), load_idx(tr_y_p).astype(np.int32)
                return MnistData(
                    _normalize(tr_x, norm), tr_y,
                    _normalize(te_x, norm), te_y, source="mnist",
                )
            # t10k-only fallback: deterministic 9k/1k split.
            log.warning(
                "train images missing under %s; splitting t10k 9k/1k", d
            )
            perm = np.random.RandomState(seed).permutation(len(te_x))
            tr_idx, te_idx = perm[:9000], perm[9000:]
            return MnistData(
                _normalize(te_x[tr_idx], norm), te_y[tr_idx],
                _normalize(te_x[te_idx], norm), te_y[te_idx],
                source="t10k-split",
            )
    if not synthetic_ok:
        raise FileNotFoundError(f"no MNIST idx files found in {dirs}")
    log.warning("no MNIST idx files found; using synthetic data")
    tr_x, tr_y, te_x, te_y = _synthetic(*synthetic_sizes, seed=seed)
    return MnistData(
        _normalize(tr_x, norm), tr_y, _normalize(te_x, norm), te_y,
        source="synthetic",
    )


def shard_indices(
    n: int, *, epoch: int, seed: int, host_id: int, num_hosts: int,
    shuffle: bool = True,
) -> np.ndarray:
    """DistributedSampler-equivalent index shard (mnist-dist2.py:100-102):
    epoch-seeded permutation, padded by wraparound to a multiple of
    num_hosts, rank-strided so every host gets the same count."""
    if shuffle:
        idx = np.random.RandomState(seed + epoch).permutation(n)
    else:
        idx = np.arange(n)
    total = -(-n // num_hosts) * num_hosts
    if total > n:
        idx = np.concatenate([idx, idx[: total - n]])
    return idx[host_id::num_hosts]


def batch_iterator(
    images: np.ndarray,
    labels: np.ndarray,
    batch_size: int,
    *,
    epoch: int = 0,
    seed: int = 0,
    host_id: int = 0,
    num_hosts: int = 1,
    shuffle: bool = True,
    drop_last: bool = True,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Per-host batched iteration with DistributedSampler sharding.

    drop_last=True keeps every batch the same shape — static shapes are what
    keep the jitted train step at one compilation (XLA semantics)."""
    idx = shard_indices(
        len(images), epoch=epoch, seed=seed, host_id=host_id,
        num_hosts=num_hosts, shuffle=shuffle,
    )
    n_full = len(idx) // batch_size
    for b in range(n_full):
        sel = idx[b * batch_size : (b + 1) * batch_size]
        yield images[sel], labels[sel]
    if not drop_last and len(idx) % batch_size:
        sel = idx[n_full * batch_size :]
        yield images[sel], labels[sel]


def native_batch_iterator(
    images: np.ndarray,
    labels: np.ndarray,
    batch_size: int,
    *,
    epoch: int = 0,
    seed: int = 0,
    host_id: int = 0,
    num_hosts: int = 1,
    shuffle: bool = True,
    n_threads: int = 2,
    n_slots: int = 4,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """``batch_iterator`` served by the native threaded BatchPool
    (native/batch_pool.cpp): the per-batch random-access row gathers run
    on C++ worker threads ahead of the consumer — torch DataLoader's
    num_workers capability for this pipeline. Identical sharding/order
    semantics (same shard_indices, drop_last); transparently falls back
    to the python iterator when the native library is unavailable or the
    data is not float32-images/int-labels shaped."""
    from .. import native

    idx = shard_indices(
        len(images), epoch=epoch, seed=seed, host_id=host_id,
        num_hosts=num_hosts, shuffle=shuffle,
    )
    pool = None
    if images.dtype == np.float32:
        try:
            pool = native.BatchPool.create(
                images, labels, idx, batch_size,
                n_threads=n_threads, n_slots=n_slots,
            )
        except Exception as e:  # never fail the train loop over the pool
            log.warning("native BatchPool unavailable (%s); python path", e)
    if pool is None:
        n_full = len(idx) // batch_size
        for b in range(n_full):
            sel = idx[b * batch_size : (b + 1) * batch_size]
            yield images[sel], labels[sel]
        return
    with pool:
        yield from pool
