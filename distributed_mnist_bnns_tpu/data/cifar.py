"""CIFAR-10 data pipeline for the stretch configs (BASELINE.json /
SURVEY.md §7 step 8: "CIFAR-10 XNOR-ResNet-18").

The reference repo is MNIST-only, so this module has no reference
counterpart — it follows the same design as mnist.py: numpy-native
parsing of the standard on-disk layouts, per-channel normalization,
graceful synthetic fallback, and reuse of the DistributedSampler-
equivalent sharding/batching from mnist.py (shard_indices /
batch_iterator are dataset-agnostic).

Supported layouts (either is found automatically under the data dir):
  * ``cifar-10-batches-py/``  — python pickle batches (data_batch_1..5,
    test_batch; each a dict with b"data" (N, 3072) uint8 rows in CHW
    order and b"labels");
  * ``cifar-10-batches-bin/`` — binary batches (data_batch_*.bin,
    test_batch.bin; records of 1 label byte + 3072 image bytes).
"""

from __future__ import annotations

import logging
import os
import pickle
from typing import Tuple

import numpy as np

from .common import ImageClassData, normalize_u8, synthetic_blobs

log = logging.getLogger(__name__)

# Standard CIFAR-10 per-channel statistics (train split).
CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)

_DEFAULT_DIRS = (
    os.path.join(os.path.dirname(__file__), "..", "..", "data"),
    "./data",
)


def _normalize(images_u8: np.ndarray, norm: str) -> np.ndarray:
    """(N, 32, 32, 3) uint8 -> normalized float32 NHWC."""
    return normalize_u8(
        images_u8, norm, stats_name="cifar", mean=CIFAR10_MEAN, std=CIFAR10_STD
    )


def _rows_to_nhwc(rows: np.ndarray) -> np.ndarray:
    """(N, 3072) uint8 CHW rows -> (N, 32, 32, 3) uint8 NHWC."""
    return rows.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)


def _load_py_batches(d: str) -> Tuple[np.ndarray, ...] | None:
    names = [f"data_batch_{i}" for i in range(1, 6)]
    if not all(os.path.exists(os.path.join(d, n)) for n in names + ["test_batch"]):
        return None

    def load(name):
        with open(os.path.join(d, name), "rb") as f:
            batch = pickle.load(f, encoding="bytes")
        return batch[b"data"], np.asarray(batch[b"labels"], np.int32)

    xs, ys = zip(*(load(n) for n in names))
    te_x, te_y = load("test_batch")
    return (
        _rows_to_nhwc(np.concatenate(xs)),
        np.concatenate(ys),
        _rows_to_nhwc(te_x),
        te_y,
    )


def _load_bin_batches(d: str) -> Tuple[np.ndarray, ...] | None:
    names = [f"data_batch_{i}.bin" for i in range(1, 6)]
    if not all(
        os.path.exists(os.path.join(d, n)) for n in names + ["test_batch.bin"]
    ):
        return None

    def load(name):
        path = os.path.join(d, name)
        size = os.path.getsize(path)
        if size == 0 or size % 3073:
            return None  # truncated/corrupt — treat the layout as absent
        n = size // 3073
        try:  # native decoder fuses the CHW->HWC transpose into the read
            from .. import native

            decoded = native.cifar_bin_decode_native(path, n)
            if decoded is not None:
                return decoded
        except Exception as e:  # pragma: no cover - fall through to numpy
            log.debug("native cifar decode failed (%s); numpy reader", e)
        rec = np.fromfile(path, np.uint8).reshape(-1, 3073)
        return _rows_to_nhwc(rec[:, 1:]), rec[:, 0].astype(np.int32)

    loaded = [load(n) for n in names + ["test_batch.bin"]]
    if any(b is None for b in loaded):
        return None
    xs, ys = zip(*loaded[:-1])
    te_x, te_y = loaded[-1]
    return np.concatenate(xs), np.concatenate(ys), te_x, te_y


def _synthetic(n_train: int, n_test: int, seed: int) -> Tuple[np.ndarray, ...]:
    return synthetic_blobs((32, 32, 3), n_train, n_test, seed)


def load_cifar10(
    data_dir: str | None = None,
    *,
    norm: str = "cifar",
    synthetic_ok: bool = True,
    synthetic_sizes: Tuple[int, int] = (50000, 10000),
    seed: int = 0,
) -> ImageClassData:
    """Load CIFAR-10 from the pickle or binary layout; synthetic fallback."""
    roots = [data_dir] if data_dir else list(_DEFAULT_DIRS)
    for root in roots:
        if root is None or not os.path.isdir(root):
            continue
        # Accept either the parent data dir or the batches dir itself.
        # The binary layout is preferred when both are present: parsing it
        # is pure numpy, whereas the pickle layout goes through
        # pickle.load, which executes arbitrary code from a hostile file —
        # only point data_dir at pickle batches you obtained from the
        # official CIFAR distribution.
        for sub, loader in (
            ("cifar-10-batches-bin", _load_bin_batches),
            ("cifar-10-batches-py", _load_py_batches),
            ("", _load_bin_batches),
            ("", _load_py_batches),
        ):
            d = os.path.join(root, sub) if sub else root
            if not os.path.isdir(d):
                continue
            loaded = loader(d)
            if loaded is not None:
                tr_x, tr_y, te_x, te_y = loaded
                return ImageClassData(
                    _normalize(tr_x, norm), tr_y,
                    _normalize(te_x, norm), te_y,
                    source="cifar10", name="cifar10",
                )
    if not synthetic_ok:
        raise FileNotFoundError(f"no CIFAR-10 batches found in {roots}")
    log.warning("no CIFAR-10 batches found; using synthetic data")
    tr_x, tr_y, te_x, te_y = _synthetic(*synthetic_sizes, seed=seed)
    return ImageClassData(
        _normalize(tr_x, norm), tr_y, _normalize(te_x, norm), te_y,
        source="synthetic", name="cifar10",
    )
