"""ImageNet-1k data pipeline for the BASELINE.json pod-scale config
("ImageNet-1k XNOR-ResNet-50 on v5p-32 pod").

The reference repo is MNIST-only (SURVEY §2.4), so this module has no
reference counterpart — it extends the mnist.py/cifar.py design to the
one dataset that cannot live in host RAM as float32 (1.28M x 224x224x3 x
4B ≈ 770 GB):

  * **In-memory subsets** (``load_imagenet``) return the same
    ``ImageClassData`` container every other pipeline uses, capped at
    ``max_train``/``max_test`` class-balanced images — enough for smoke
    runs, tests, and the CLI, with a synthetic fallback shaped exactly
    like the real thing (H x W x 3 uint8, ``n_classes`` up to 1000).
  * **Streaming epochs** (``open_imagenet_stream`` -> ``ImageNetStream``)
    decode JPEGs on host worker threads per batch, reusing the
    DistributedSampler-equivalent ``shard_indices`` (data/mnist.py) for
    multi-host sharding — the full-dataset path.

TPU-first division of labor: the host does the minimal deterministic
decode (resize shorter side, center crop, normalize); *random*
augmentation (crop jitter + flip) runs on device inside the train step
(ops/augment.py, ``--augment``), so the host never becomes the
bottleneck doing per-sample random transforms the VPU does for free.

Supported on-disk layouts (found automatically under the data dir):
  * folder: ``train/<wnid>/*.JPEG`` and ``val/<wnid>/*.JPEG`` (the
    standard torchvision ImageFolder layout);
  * per-class tars: ``train/<wnid>.tar`` — exactly what unpacking the
    official ``ILSVRC2012_img_train.tar`` one level produces.
"""

from __future__ import annotations

import io
import logging
import os
import tarfile
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .common import ImageClassData, normalize_u8
from .mnist import shard_indices

log = logging.getLogger(__name__)

# Standard ImageNet per-channel statistics (train split).
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)

_DEFAULT_DIRS = (
    os.path.join(os.path.dirname(__file__), "..", "..", "data"),
    "./data",
)
_IMG_EXTS = (".jpeg", ".jpg", ".png")


def _normalize(images_u8: np.ndarray, norm: str) -> np.ndarray:
    """(N, H, W, 3) uint8 -> normalized float32 NHWC."""
    return normalize_u8(
        images_u8, norm, stats_name="imagenet",
        mean=IMAGENET_MEAN, std=IMAGENET_STD,
    )


# ---------------------------------------------------------------------------
# Decoding


def _decode_u8(data: bytes, image_size: int) -> np.ndarray:
    """JPEG/PNG bytes -> (image_size, image_size, 3) uint8.

    The standard eval transform: resize the shorter side to
    256/224 * image_size (bilinear), center-crop image_size. Train-time
    randomness is applied later on device (ops/augment.py)."""
    from PIL import Image

    with Image.open(io.BytesIO(data)) as im:
        im = im.convert("RGB")
        short = round(image_size * 256 / 224)
        w, h = im.size
        if w <= h:
            w, h = short, max(1, round(h * short / w))
        else:
            w, h = max(1, round(w * short / h)), short
        im = im.resize((w, h), Image.BILINEAR)
        left = (w - image_size) // 2
        top = (h - image_size) // 2
        im = im.crop((left, top, left + image_size, top + image_size))
        return np.asarray(im, np.uint8)


class _TarCache:
    """Per-thread cache of open TarFile handles (TarFile is not
    thread-safe; each decode worker keeps its own handles open instead of
    re-opening the archive per member)."""

    def __init__(self):
        self._local = threading.local()

    def member_bytes(self, tar_path: str, member: str) -> bytes:
        handles = getattr(self._local, "handles", None)
        if handles is None:
            handles = self._local.handles = {}
        tf = handles.get(tar_path)
        if tf is None:
            tf = handles[tar_path] = tarfile.open(tar_path, "r")
        f = tf.extractfile(member)
        if f is None:
            raise FileNotFoundError(f"{member} not in {tar_path}")
        with f:
            return f.read()


# ---------------------------------------------------------------------------
# Index


@dataclass
class ImageNetIndex:
    """A split's item index: (source, label) pairs where source is either
    a filesystem path or a (tar_path, member_name) pair."""

    items: List[Tuple]          # [(path_or_(tar,member), int label), ...]
    wnids: Sequence[str]        # sorted; label i <-> wnids[i]
    split: str                  # "train" | "val"

    @property
    def n_classes(self) -> int:
        return len(self.wnids)

    def labels(self) -> np.ndarray:
        return np.asarray([lb for _, lb in self.items], np.int32)


def _tar_members(tar_path: str) -> Optional[List[str]]:
    try:
        with tarfile.open(tar_path, "r") as tf:
            return sorted(
                m.name for m in tf.getmembers()
                if m.isfile() and m.name.lower().endswith(_IMG_EXTS)
            )
    except tarfile.TarError:
        log.warning("skipping unreadable tar %s", tar_path)
        return None


def _index_split(
    split_dir: str, wnids: Optional[Sequence[str]] = None, workers: int = 8
) -> Optional[ImageNetIndex]:
    """Index one split dir in either supported layout; None if absent.

    ``wnids``: an existing label space to index against (the train
    split's) — items whose wnid is not in it are dropped with a warning,
    so val labels always mean the same class as train labels even when
    the two splits' wnid sets disagree (partial downloads)."""
    if not os.path.isdir(split_dir):
        return None
    entries = sorted(os.listdir(split_dir))
    wnid_dirs = [
        e for e in entries if os.path.isdir(os.path.join(split_dir, e))
    ]
    wnid_tars = [e for e in entries if e.endswith(".tar")]
    # wnid -> sorted sources within that class
    per_class: dict = {}
    if wnid_dirs:
        for wnid in wnid_dirs:
            d = os.path.join(split_dir, wnid)
            per_class[wnid] = [
                os.path.join(d, name)
                for name in sorted(os.listdir(d))
                if name.lower().endswith(_IMG_EXTS)
            ]
    elif wnid_tars:
        # Header scans are independent per archive: parallelize (1000
        # per-class tars scanned serially would gate first-batch latency).
        paths = [os.path.join(split_dir, t) for t in wnid_tars]
        with ThreadPoolExecutor(max_workers=workers) as pool:
            scanned = list(pool.map(_tar_members, paths))
        for tar_name, tar_path, members in zip(wnid_tars, paths, scanned):
            if members is None:
                continue  # unreadable: class excluded from the label space
            per_class[tar_name[: -len(".tar")]] = [
                (tar_path, m) for m in members
            ]
    else:
        return None
    if wnids is None:
        wnids = sorted(per_class)
    mapping = {w: i for i, w in enumerate(wnids)}
    dropped = sorted(set(per_class) - set(mapping))
    if dropped:
        log.warning(
            "%s: dropping %d wnid(s) absent from the train label space "
            "(e.g. %s)", split_dir, len(dropped), dropped[:3],
        )
    items: List[Tuple] = []
    for wnid in sorted(per_class):
        if wnid in mapping:
            items.extend((src, mapping[wnid]) for src in per_class[wnid])
    if not items:
        return None
    return ImageNetIndex(
        items=items, wnids=list(wnids), split=os.path.basename(split_dir)
    )


def _find_split_dir(data_dir: Optional[str], split: str) -> Optional[str]:
    roots = [data_dir] if data_dir else list(_DEFAULT_DIRS)
    for root in roots:
        if root is None or not os.path.isdir(root):
            continue
        for sub in (os.path.join("imagenet", split), split):
            d = os.path.join(root, sub)
            if os.path.isdir(d):
                return d
    return None


# ---------------------------------------------------------------------------
# Streaming


@dataclass
class ImageNetStream:
    """Streaming split: decodes per batch on worker threads, shards with
    the DistributedSampler-equivalent ``shard_indices``. The full-scale
    path — nothing here holds more than ``workers * batch_size`` decoded
    images at once."""

    index: ImageNetIndex
    image_size: int = 224
    norm: str = "imagenet"
    workers: int = 8
    _tars: _TarCache = field(default_factory=_TarCache, repr=False)

    def __len__(self) -> int:
        return len(self.index.items)

    @property
    def n_classes(self) -> int:
        return self.index.n_classes

    def _decode_item(self, i: int) -> np.ndarray:
        src, _ = self.index.items[i]
        if isinstance(src, tuple):
            data = self._tars.member_bytes(*src)
        else:
            with open(src, "rb") as f:
                data = f.read()
        return _decode_u8(data, self.image_size)

    def decode_indices(self, idx: Sequence[int]) -> np.ndarray:
        """Decode a batch of items to normalized float32 NHWC."""
        with ThreadPoolExecutor(max_workers=self.workers) as pool:
            imgs = list(pool.map(self._decode_item, idx))
        return _normalize(np.stack(imgs), self.norm)

    def batches(
        self,
        batch_size: int,
        *,
        epoch: int = 0,
        seed: int = 0,
        host_id: int = 0,
        num_hosts: int = 1,
        shuffle: bool = True,
        drop_last: bool = True,
    ):
        """Yield (images, labels) batches of this host's epoch shard."""
        labels = self.index.labels()
        idx = shard_indices(
            len(self), epoch=epoch, seed=seed, host_id=host_id,
            num_hosts=num_hosts, shuffle=shuffle,
        )
        n_full = len(idx) // batch_size
        stop = n_full * batch_size if drop_last else len(idx)
        pool = ThreadPoolExecutor(max_workers=self.workers)
        try:
            for start in range(0, stop, batch_size):
                chunk = idx[start : start + batch_size]
                imgs = list(pool.map(self._decode_item, chunk))
                yield (
                    _normalize(np.stack(imgs), self.norm),
                    labels[chunk],
                )
        finally:
            pool.shutdown(wait=False)

    def materialize(
        self, max_images: Optional[int], *, seed: int = 0
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Decode a class-balanced subset (or everything if max_images is
        None) into memory: (normalized float32 images, int32 labels)."""
        labels = self.index.labels()
        if max_images is None or max_images >= len(self):
            take = np.arange(len(self))
        else:
            rng = np.random.RandomState(seed)
            take = _balanced_subset(labels, max_images, rng)
        return self.decode_indices(take), labels[take]


def _balanced_subset(
    labels: np.ndarray, n: int, rng: np.random.RandomState
) -> np.ndarray:
    """Round-robin over classes so a small cap still covers all of them."""
    order = rng.permutation(len(labels))
    by_class: dict = {}
    for i in order:
        by_class.setdefault(int(labels[i]), []).append(i)
    out: List[int] = []
    queues = list(by_class.values())
    while len(out) < n and queues:
        queues = [q for q in queues if q]
        for q in queues:
            if len(out) >= n:
                break
            out.append(q.pop())
    return np.asarray(out, np.int64)


def open_imagenet_stream(
    data_dir: Optional[str] = None,
    split: str = "train",
    *,
    image_size: int = 224,
    norm: str = "imagenet",
    workers: int = 8,
    wnids: Optional[Sequence[str]] = None,
) -> Optional[ImageNetStream]:
    """Open a streaming view of an on-disk split; None if not found.

    Pass the train stream's ``index.wnids`` as ``wnids`` when opening a
    val stream so both splits share one label space."""
    d = _find_split_dir(data_dir, split)
    index = _index_split(d, wnids=wnids, workers=workers) if d else None
    if index is None:
        return None
    return ImageNetStream(
        index=index, image_size=image_size, norm=norm, workers=workers
    )


# ---------------------------------------------------------------------------
# Synthetic fallback + in-memory loader


def synthetic_imagenet(
    image_shape: Tuple[int, int, int],
    n_train: int,
    n_test: int,
    seed: int,
    n_classes: int = 1000,
) -> Tuple[np.ndarray, ...]:
    """ImageNet-shaped class-conditional synthetic data.

    common.synthetic_blobs stores one full-resolution template per class —
    at 1000 x 224x224x3 that is ~1.2 GB of templates alone. Here each
    class gets an 8x8x3 coarse pattern (96 KB for all 1000 classes),
    nearest-upsampled to full resolution per sample, plus pixel noise:
    same statistical role (linearly separable, correctly shaped uint8),
    O(n_samples) memory."""
    H, W, C = image_shape
    rng = np.random.RandomState(seed)
    coarse = rng.randint(0, 256, size=(n_classes, 8, 8, C), dtype=np.int16)

    def make(n: int):
        labels = rng.randint(0, n_classes, size=n).astype(np.int32)
        t = coarse[labels]                                 # (n, 8, 8, C)
        t = np.repeat(np.repeat(t, -(-H // 8), 1), -(-W // 8), 2)[:, :H, :W]
        noise = rng.randint(-32, 33, size=t.shape, dtype=np.int16)
        return np.clip(t + noise, 0, 255).astype(np.uint8), labels

    tr_x, tr_y = make(n_train)
    te_x, te_y = make(n_test)
    return tr_x, tr_y, te_x, te_y


def load_imagenet(
    data_dir: Optional[str] = None,
    *,
    norm: str = "imagenet",
    image_size: int = 224,
    max_train: Optional[int] = 4096,
    max_test: Optional[int] = 1024,
    synthetic_ok: bool = True,
    synthetic_sizes: Tuple[int, int] = (1024, 256),
    synthetic_classes: int = 1000,
    seed: int = 0,
    workers: int = 8,
) -> ImageClassData:
    """In-memory ImageNet subset as an ``ImageClassData`` (the container
    the Trainer and every parallel wrapper duck-type against).

    Real data: class-balanced ``max_train``/``max_test`` caps bound host
    memory (the full set cannot fit; use ``open_imagenet_stream`` for
    whole-dataset epochs). Falls back to ImageNet-shaped synthetic data
    when no on-disk layout is found."""
    train = open_imagenet_stream(
        data_dir, "train", image_size=image_size, norm=norm, workers=workers
    )
    if train is not None:
        val = open_imagenet_stream(
            data_dir, "val", image_size=image_size, norm=norm,
            workers=workers, wnids=train.index.wnids,
        )
        tr_x, tr_y = train.materialize(max_train, seed=seed)
        if val is not None:
            te_x, te_y = val.materialize(max_test, seed=seed)
        else:  # no val split on disk: hold out from the train subset
            n_hold = max(1, len(tr_y) // 10)
            te_x, te_y = tr_x[:n_hold], tr_y[:n_hold]
            tr_x, tr_y = tr_x[n_hold:], tr_y[n_hold:]
        return ImageClassData(
            tr_x, tr_y, te_x, te_y,
            source="imagenet", name="imagenet",
            n_classes=train.n_classes,
        )
    if not synthetic_ok:
        raise FileNotFoundError(
            f"no ImageNet layout (train/<wnid>/ dirs or <wnid>.tar files) "
            f"found under {data_dir or _DEFAULT_DIRS}"
        )
    log.warning("no ImageNet layout found; using synthetic data")
    tr_x, tr_y, te_x, te_y = synthetic_imagenet(
        (image_size, image_size, 3), *synthetic_sizes, seed=seed,
        n_classes=synthetic_classes,
    )
    return ImageClassData(
        _normalize(tr_x, norm), tr_y, _normalize(te_x, norm), te_y,
        source="synthetic", name="imagenet", n_classes=synthetic_classes,
    )
