"""Optimizer registry + epoch-indexed "regime" scheduling.

Parity with the reference's ``__optimizers`` name->class dict (8 torch
optimizers, utils.py:104-113) and ``adjust_optimizer`` (utils.py:116-139):
a regime maps epoch -> settings dict; settings are *sticky* — the effective
config at epoch E is the merge of every entry with key <= E, replayed from
epoch 0 (exactly the reference's replay loop, utils.py:128-135).

Functional-JAX adaptation: hyperparameters (lr, momentum, ...) are updated
in place via optax.inject_hyperparams without resetting optimizer state;
changing the optimizer *class* mid-run rebuilds the transform with fresh
state (the reference's adjust_optimizer also reconstructs the torch
optimizer class, losing its state, utils.py:120-126 — same semantics).

``asgd`` (torch ASGD) is provided as SGD + Polyak tail averaging: the
transform keeps a running parameter average in its state (the torch
optimizer's ``ax`` buffer) while stepping as plain SGD.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, NamedTuple

import jax
import jax.numpy as jnp
import optax


class _AsgdAvgState(NamedTuple):
    inner: Any
    avg: Any
    count: jnp.ndarray


def _asgd(learning_rate: float = 0.01) -> optax.GradientTransformation:
    """SGD with Polyak parameter averaging kept in state (torch ASGD's ax)."""
    inner = optax.sgd(learning_rate)

    def init(params):
        return _AsgdAvgState(
            inner=inner.init(params),
            avg=jax.tree.map(jnp.asarray, params),
            count=jnp.zeros((), jnp.int32),
        )

    def update(updates, state, params=None):
        new_updates, new_inner = inner.update(updates, state.inner, params)
        if params is not None:
            new_params = optax.apply_updates(params, new_updates)
            c = state.count + 1
            avg = jax.tree.map(
                lambda a, p: a + (p - a) / c.astype(p.dtype), state.avg, new_params
            )
        else:  # pragma: no cover - params always passed in this framework
            avg, c = state.avg, state.count
        return new_updates, _AsgdAvgState(new_inner, avg, c)

    return optax.GradientTransformation(init, update)


OPTIMIZER_REGISTRY: Dict[str, Callable[..., optax.GradientTransformation]] = {
    "sgd": optax.sgd,
    "asgd": _asgd,
    "adam": optax.adam,
    "adamax": optax.adamax,
    "adagrad": optax.adagrad,
    "adadelta": optax.adadelta,
    "rprop": optax.rprop,
    "rmsprop": optax.rmsprop,
    # Large-batch optimizers (beyond reference parity): layerwise trust
    # ratios keep the bench's batch-4096 regime trainable at reference
    # accuracy recipes scaled up — the standard TPU large-batch choices.
    "lars": optax.lars,
    "lamb": optax.lamb,
}

# Hyperparameter keys accepted per optimizer (anything else in a regime
# entry is ignored with the same tolerance as torch param_group updates).
_HP_KEYS = ("learning_rate", "momentum", "b1", "b2", "eps", "weight_decay")


def make_optimizer(
    name: str, learning_rate: float, *, clip_grad_norm: float | None = None,
    **kwargs: Any,
) -> optax.GradientTransformation:
    """Build a registry optimizer wrapped in inject_hyperparams so the
    learning rate (and other numeric HPs) can be retuned per epoch without
    resetting moment state.

    ``clip_grad_norm`` prepends global-norm gradient clipping INSIDE the
    inject_hyperparams wrapper — the hyperparams dict stays the outermost
    state attribute, so the Trainer's per-epoch lr/regime writes keep
    working (chaining outside would bury it and silently disable the lr
    schedule)."""
    try:
        base_ctor = OPTIMIZER_REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown optimizer {name!r}; available: {sorted(OPTIMIZER_REGISTRY)}"
        ) from None
    if clip_grad_norm is not None:
        if clip_grad_norm <= 0:
            raise ValueError(f"clip_grad_norm must be > 0, got {clip_grad_norm}")

        def ctor(*a, **kw):
            return optax.chain(
                optax.clip_by_global_norm(clip_grad_norm), base_ctor(*a, **kw)
            )

        # inject_hyperparams introspects the ctor signature:
        ctor.__signature__ = inspect.signature(base_ctor)
    else:
        ctor = base_ctor
    # Materialize numeric values for HP keys the ctor accepts with a
    # non-numeric default (e.g. sgd's momentum=None): inject_hyperparams
    # only exposes numeric args, and a regime must be able to retune any
    # param-group key in place (adjust_optimizer, utils.py:116-139).
    # momentum=0.0 is mathematically identical to momentum=None.
    sig = inspect.signature(ctor)
    for k in _HP_KEYS:
        if k == "learning_rate":  # passed explicitly below (adadelta's
            continue              # default is None — don't duplicate it)
        p = sig.parameters.get(k)
        if p is not None and p.default is None and k not in kwargs:
            kwargs[k] = 0.0
    return optax.inject_hyperparams(ctor)(learning_rate=learning_rate, **kwargs)


def regime_hp_kwargs(name: str, cfg: Dict[str, Any]) -> Dict[str, Any]:
    """The HP entries of a regime config that optimizer ``name``'s ctor
    accepts (others are ignored — the same tolerance torch shows for
    unknown param-group keys)."""
    ctor = OPTIMIZER_REGISTRY[name.lower()]
    sig = inspect.signature(ctor)
    return {
        k: cfg[k]
        for k in _HP_KEYS
        if k != "learning_rate" and k in cfg and k in sig.parameters
    }


class RegimeSchedule:
    """Epoch-indexed optimizer regime with sticky replay (utils.py:116-139).

    regime: {epoch: {"optimizer": name, "learning_rate": f, ...}} or a
    callable epoch -> dict. ``config_at(epoch)`` merges entries 0..epoch.
    """

    def __init__(self, regime: Dict[int, Dict[str, Any]] | Callable[[int], Dict] | None):
        self.regime = regime

    def config_at(self, epoch: int) -> Dict[str, Any]:
        if self.regime is None:
            return {}
        if callable(self.regime):
            merged: Dict[str, Any] = {}
            for e in range(epoch + 1):
                merged.update(self.regime(e) or {})
            return merged
        merged = {}
        for e in sorted(self.regime):
            if e <= epoch:
                merged.update(self.regime[e])
        return merged

    def optimizer_changed(self, epoch: int) -> bool:
        """Did the optimizer *class* change exactly at this epoch?"""
        if epoch == 0:
            return False
        prev = self.config_at(epoch - 1).get("optimizer")
        now = self.config_at(epoch).get("optimizer")
        return now is not None and now != prev

    def apply_hyperparams(self, opt_state: Any, epoch: int) -> Any:
        """Write the regime's numeric HPs for this epoch into an
        inject_hyperparams state (no moment reset)."""
        cfg = self.config_at(epoch)
        hp = getattr(opt_state, "hyperparams", None)
        if hp is None:
            return opt_state
        for k in _HP_KEYS:
            if k in cfg and k in hp:
                hp[k] = jnp.asarray(cfg[k], dtype=jnp.asarray(hp[k]).dtype)
        return opt_state
