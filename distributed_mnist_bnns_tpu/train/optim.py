"""Optimizer registry + epoch-indexed "regime" scheduling.

Parity with the reference's ``__optimizers`` name->class dict (8 torch
optimizers, utils.py:104-113) and ``adjust_optimizer`` (utils.py:116-139):
a regime maps epoch -> settings dict; settings are *sticky* — the effective
config at epoch E is the merge of every entry with key <= E, replayed from
epoch 0 (exactly the reference's replay loop, utils.py:128-135).

Functional-JAX adaptation: hyperparameters (lr, momentum, ...) are updated
in place via optax.inject_hyperparams without resetting optimizer state;
changing the optimizer *class* mid-run rebuilds the transform with fresh
state (the reference's adjust_optimizer also reconstructs the torch
optimizer class, losing its state, utils.py:120-126 — same semantics).

``asgd`` (torch ASGD) is provided as SGD + Polyak tail averaging: the
transform keeps a running parameter average in its state (the torch
optimizer's ``ax`` buffer) while stepping as plain SGD.

SPMD lockstep contract (``sign_compress`` / ``sign_compress_fsdp``):
both transforms' ``update`` issue a fixed collective schedule derived
from the :class:`~..ops.comm_compress.CommPlan` alone — never from
gradient values or ``axis_index`` — so every process in the mesh runs
the identical (op, axis, shape) sequence. ``analysis/spmd.py`` records
and lockstep-checks exactly these programs (plus the post-remesh step)
at world 2/4/8 in CI's ``spmd-lockstep`` job; a value-dependent branch
around an exchange call would hang a real multi-host fleet and is what
lint rules JG012/JG014 exist to catch.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, NamedTuple, Optional

import jax
import jax.flatten_util  # noqa: F401  (jax.flatten_util.ravel_pytree)
import jax.numpy as jnp
import optax

from ..ops.comm_compress import (
    CommPlan,
    all_gather_compressed,
    exchange,
    make_plan,
    pad_flat,
    reduce_scatter_compressed,
    tree_size,
)


class _AsgdAvgState(NamedTuple):
    inner: Any
    avg: Any
    count: jnp.ndarray


def _asgd(learning_rate: float = 0.01) -> optax.GradientTransformation:
    """SGD with Polyak parameter averaging kept in state (torch ASGD's ax)."""
    inner = optax.sgd(learning_rate)

    def init(params):
        return _AsgdAvgState(
            inner=inner.init(params),
            avg=jax.tree.map(jnp.asarray, params),
            count=jnp.zeros((), jnp.int32),
        )

    def update(updates, state, params=None):
        new_updates, new_inner = inner.update(updates, state.inner, params)
        if params is not None:
            new_params = optax.apply_updates(params, new_updates)
            c = state.count + 1
            avg = jax.tree.map(
                lambda a, p: a + (p - a) / c.astype(p.dtype), state.avg, new_params
            )
        else:  # pragma: no cover - params always passed in this framework
            avg, c = state.avg, state.count
        return new_updates, _AsgdAvgState(new_inner, avg, c)

    return optax.GradientTransformation(init, update)


class SignCompressState(NamedTuple):
    """Error-feedback buffers for the 1-bit gradient exchange
    (ops/comm_compress, PERF.md "Gradient comms").

    Both carry a leading ``world`` axis — row *i* is worker *i*'s
    residual — so the buffers are ordinary global arrays in the
    checkpointed optimizer state (bitwise save/restore, the resilience
    invariant) while the compressed shard_map step shards that axis
    over 'data' (parallel/fsdp.compressed_state_specs): per-device cost
    is one fp32 residual, the same budget as a momentum buffer.

    ef_residual:  (world, padded) worker compression error — what the
                  worker's corrected gradient lost to sign quantization
                  (EF-SignSGD, Karimireddy et al., 2019).
    ef_residual2: (world, padded/world) segment-owner requantization
                  error from the exchange's second compressed phase
                  (the "server error" of 1-bit Adam).
    """

    ef_residual: jnp.ndarray
    ef_residual2: jnp.ndarray


def sign_compress(
    *,
    mode: str,
    world: int = 1,
    axis_name: Optional[str] = None,
    local_axis_name: Optional[str] = None,
    bucket_size: int = 1024,
    chunks: int = 4,
) -> optax.GradientTransformation:
    """1-bit gradient exchange as an optax transformation.

    Chain it in FRONT of the base optimizer: ``update`` flattens the
    incoming (local, per-worker) gradients, sign-compresses them per
    bucket, runs the two-phase compressed exchange over ``axis_name``
    (ops/comm_compress.exchange — this IS the DP all-reduce, so the
    step that hosts it must not pmean gradients again), and hands the
    decoded global update downstream. ``mode="sign_ef"`` additionally
    feeds both compression residuals back into the next step's input
    (held in the state, see SignCompressState); ``mode="sign"`` is the
    stateless Bernstein majority vote.

    With ``axis_name`` set, ``update`` must run inside the shard_map
    that owns that axis (the local view of the state buffers then has
    the leading axis sliced to 1); ``init`` always runs outside, on the
    global params. ``world=1`` needs no mesh and is the NumPy-oracle
    test configuration.

    Hierarchical form: ``local_axis_name`` names the intra-host mesh
    axis (ops/comm_compress.hier_exchange). The incoming gradients are
    fp32-pmean'd over it FIRST — the in-host ring reduce on the fast
    interconnect — and the 1-bit exchange then runs over ``axis_name``
    (the slow inter-host link) only, with ``world`` = the number of
    HOSTS. Every device on a host carries the identical post-pmean
    gradient, so the per-host EF rows are replicated over the local
    axis and the collective schedule stays device-independent.
    """
    if mode not in ("sign", "sign_ef"):
        raise ValueError(
            f"unknown compression mode {mode!r} (have: sign, sign_ef)"
        )
    if axis_name is None and world != 1:
        raise ValueError("world > 1 requires an axis_name to exchange over")
    if local_axis_name is not None and axis_name is None:
        raise ValueError(
            "local_axis_name (hierarchical exchange) requires axis_name "
            "for the inter-host phase"
        )

    def _plan(n: int) -> CommPlan:
        return make_plan(
            n, world=world, mode=mode, bucket_size=bucket_size,
            chunks=chunks,
        )

    def init(params):
        if mode != "sign_ef":
            return optax.EmptyState()
        plan = _plan(tree_size(params))
        return SignCompressState(
            ef_residual=jnp.zeros((world, plan.padded), jnp.float32),
            ef_residual2=jnp.zeros((world, plan.seg), jnp.float32),
        )

    def update(updates, state, params=None):
        del params
        flat, unravel = jax.flatten_util.ravel_pytree(updates)
        plan = _plan(flat.size)
        flat = pad_flat(flat.astype(jnp.float32), plan)
        if local_axis_name is not None:
            # Intra-host fp32 ring reduce (the hierarchical fast-link
            # phase): after this every device on the host carries the
            # host-mean gradient and the 1-bit exchange below runs over
            # the inter-host axis only.
            flat = jax.lax.pmean(flat, local_axis_name)
        if mode == "sign_ef":
            corrected = flat + state.ef_residual[0]
            e2 = state.ef_residual2[0]
        else:
            corrected, e2 = flat, None
        combined, sent, e2_new = exchange(
            corrected, plan, axis_name=axis_name, e2=e2
        )
        new_updates = unravel(combined[: plan.n_params])
        if mode != "sign_ef":
            return new_updates, state
        # The pad tail never reaches the model (combined is sliced
        # before unraveling); zero its residual so phantom error can't
        # pollute the partial bucket's scale on later steps. e2 covers
        # one segment; only the last worker's segment holds pad.
        e1_new = (corrected - sent).at[plan.n_params:].set(0.0)
        if axis_name is not None:
            seg0 = jax.lax.axis_index(axis_name) * plan.seg
        else:
            seg0 = 0
        valid2 = seg0 + jnp.arange(plan.seg) < plan.n_params
        e2_new = jnp.where(valid2, e2_new, 0.0)
        return new_updates, SignCompressState(
            ef_residual=e1_new[None], ef_residual2=e2_new[None]
        )

    return optax.GradientTransformation(init, update)


class FsdpCompressState(NamedTuple):
    """State of the compressed-FSDP exchange (``sign_compress_fsdp``).

    Like :class:`SignCompressState`, every array carries a leading
    ``world`` axis — row *i* belongs to worker *i* — so the buffers are
    ordinary global arrays in the checkpointed optimizer state (bitwise
    save/restore) while the compressed shard_map step shards that axis
    over 'data' (parallel/fsdp.compressed_state_specs). The difference
    from the DP layout: the BASE optimizer's state rides in here too,
    flattened to the (world, seg) ZeRO segment layout, because under
    FSDP the segment owner — not every replica — runs the optimizer.

    ef_residual:  (world, padded) worker gradient-compression error
                  (EF-SignSGD; (world, 0) in stateless ``sign`` mode).
    ef_residual2: (world, seg) segment-owner residual of the UPDATE
                  broadcast — the 1-bit param-all-gather's quantization
                  error, fed into the next step's delta (1-bit Adam's
                  server error applied to the model-update stream;
                  (world, 0) in ``sign`` mode).
    inner:        the wrapped base optimizer's state over the
                  (world, seg) flat param segments (e.g. adam's mu/nu
                  rows — per-device cost 1/N of the replicated moments).
    """

    ef_residual: jnp.ndarray
    ef_residual2: jnp.ndarray
    inner: Any


def sign_compress_fsdp(
    inner: optax.GradientTransformation,
    *,
    mode: str,
    world: int = 1,
    axis_name: Optional[str] = None,
    bucket_size: int = 1024,
    chunks: int = 4,
) -> optax.GradientTransformation:
    """1-bit compressed FSDP/ZeRO exchange wrapping a base optimizer.

    Where :func:`sign_compress` chains in FRONT of a replicated
    optimizer (every worker redundantly applies the decoded global
    gradient), this transform puts the optimizer INSIDE the exchange,
    the ZeRO way:

      1. compressed reduce-scatter: sign planes + fp32 bucket scales
         ``all_to_all`` to segment owners, each owner combining the
         ``world`` contributions (ops/comm_compress.reduce_scatter_
         compressed) — the gradient never travels in fp32;
      2. sharded update: the owner runs ``inner`` on its (1, seg) flat
         segment with its (1, seg) moment rows — optimizer state is
         sharded 1/N over 'data', the ZeRO property;
      3. compressed all-gather: the owner's UPDATE DELTA (not the fp32
         param shard) broadcasts as packed bitplanes; every worker
         applies the identical decoded delta, so params stay replicated
         and bitwise consistent without an fp32 param all-gather.

    ``mode="sign_ef"`` keeps two-stage error feedback: the worker
    residual absorbs step 1's quantization loss, the owner residual
    absorbs step 3's (both in the state, ZeRO-sharded). ``mode="sign"``
    is the stateless majority vote with an unguarded delta broadcast.

    ``inner`` must be ELEMENTWISE (sgd/adam/adamax/adagrad/adadelta/
    rprop/rmsprop/asgd): it sees flattened segments, so layerwise
    optimizers (lars/lamb trust ratios) would silently compute norms
    over arbitrary slices — the Trainer rejects them up front.

    Like ``sign_compress``: with ``axis_name`` set, ``update`` must run
    inside the shard_map that owns the axis (state buffers sliced to a
    leading axis of 1); ``init`` always runs outside on the global
    params; ``world=1`` degenerates to the collective-free local form
    (the NumPy-oracle test configuration). The transform is pure —
    no Python-level state — so it is scan-body-safe: ``lax.scan`` of
    the step body fuses multiple exchanges into one dispatch with the
    per-chunk overlap intact inside every iteration.
    """
    if mode not in ("sign", "sign_ef"):
        raise ValueError(
            f"unknown compression mode {mode!r} (have: sign, sign_ef)"
        )
    if axis_name is None and world != 1:
        raise ValueError("world > 1 requires an axis_name to exchange over")

    def _plan(n: int) -> CommPlan:
        return make_plan(
            n, world=world, mode=mode, bucket_size=bucket_size,
            chunks=chunks, layout="fsdp",
        )

    def _seg_params(params, plan: CommPlan):
        """The (world, seg) ZeRO layout of the flattened params."""
        flat, _ = jax.flatten_util.ravel_pytree(params)
        flat = pad_flat(flat.astype(jnp.float32), plan)
        return flat.reshape(world, plan.seg)

    def init(params):
        plan = _plan(tree_size(params))
        ef_rows = plan.padded if mode == "sign_ef" else 0
        ef2_rows = plan.seg if mode == "sign_ef" else 0
        return FsdpCompressState(
            ef_residual=jnp.zeros((world, ef_rows), jnp.float32),
            ef_residual2=jnp.zeros((world, ef2_rows), jnp.float32),
            inner=inner.init(_seg_params(params, plan)),
        )

    def update(updates, state, params=None):
        flat, unravel = jax.flatten_util.ravel_pytree(updates)
        plan = _plan(flat.size)
        flat = pad_flat(flat.astype(jnp.float32), plan)
        if mode == "sign_ef":
            corrected = flat + state.ef_residual[0]
        else:
            corrected = flat
        # phase rs: every worker's planes for segment j land on owner j
        own, sent = reduce_scatter_compressed(
            corrected, plan, axis_name=axis_name
        )
        # ZeRO update: the owner's sharded moment rows see the exact
        # combined gradient of the segment it owns. The local view of
        # the inner state has its world axis sliced to 1, matching the
        # (1, seg) gradient row.
        if params is not None:
            seg_all = _seg_params(params, plan)
            idx = (
                jax.lax.axis_index(axis_name) if axis_name is not None
                else 0
            )
            seg_p = jax.lax.dynamic_slice_in_dim(seg_all, idx, 1, axis=0)
        else:  # pragma: no cover - params always passed in this framework
            seg_p = None
        delta, new_inner = inner.update(own[None], state.inner, seg_p)
        delta = delta[0]                              # (seg,)
        if mode == "sign_ef":
            delta = delta + state.ef_residual2[0]
        # phase ag: the 1-bit update delta replaces the fp32 param
        # all-gather; every worker decodes the identical full delta.
        full, own_dec = all_gather_compressed(
            delta, plan, axis_name=axis_name
        )
        new_updates = unravel(full[: plan.n_params])
        if mode != "sign_ef":
            return new_updates, FsdpCompressState(
                ef_residual=state.ef_residual,
                ef_residual2=state.ef_residual2,
                inner=new_inner,
            )
        # Zero the residual tails covering pad positions (they never
        # reach the model — see sign_compress for the rationale).
        e1_new = (corrected - sent).at[plan.n_params:].set(0.0)
        if axis_name is not None:
            seg0 = jax.lax.axis_index(axis_name) * plan.seg
        else:
            seg0 = 0
        valid2 = seg0 + jnp.arange(plan.seg) < plan.n_params
        e2_new = jnp.where(valid2, delta - own_dec, 0.0)
        return new_updates, FsdpCompressState(
            ef_residual=e1_new[None], ef_residual2=e2_new[None],
            inner=new_inner,
        )

    return optax.GradientTransformation(init, update)


OPTIMIZER_REGISTRY: Dict[str, Callable[..., optax.GradientTransformation]] = {
    "sgd": optax.sgd,
    "asgd": _asgd,
    "adam": optax.adam,
    "adamax": optax.adamax,
    "adagrad": optax.adagrad,
    "adadelta": optax.adadelta,
    "rprop": optax.rprop,
    "rmsprop": optax.rmsprop,
    # Large-batch optimizers (beyond reference parity): layerwise trust
    # ratios keep the bench's batch-4096 regime trainable at reference
    # accuracy recipes scaled up — the standard TPU large-batch choices.
    "lars": optax.lars,
    "lamb": optax.lamb,
}

# Hyperparameter keys accepted per optimizer (anything else in a regime
# entry is ignored with the same tolerance as torch param_group updates).
_HP_KEYS = ("learning_rate", "momentum", "b1", "b2", "eps", "weight_decay")


def make_optimizer(
    name: str, learning_rate: float, *, clip_grad_norm: float | None = None,
    grad_transform: optax.GradientTransformation | None = None,
    grad_transform_wrapper: Callable[
        [optax.GradientTransformation], optax.GradientTransformation
    ] | None = None,
    **kwargs: Any,
) -> optax.GradientTransformation:
    """Build a registry optimizer wrapped in inject_hyperparams so the
    learning rate (and other numeric HPs) can be retuned per epoch without
    resetting moment state.

    ``clip_grad_norm`` prepends global-norm gradient clipping INSIDE the
    inject_hyperparams wrapper — the hyperparams dict stays the outermost
    state attribute, so the Trainer's per-epoch lr/regime writes keep
    working (chaining outside would bury it and silently disable the lr
    schedule). ``grad_transform`` (e.g. ``sign_compress``) chains after
    the clip and before the optimizer, inside the same wrapper for the
    same reason — its state (the EF residuals) rides in ``opt_state``
    and therefore checkpoints with it. ``grad_transform_wrapper``
    (e.g. ``sign_compress_fsdp``) instead WRAPS the base optimizer —
    the compressed-FSDP exchange runs the optimizer inside itself on
    the owner's ZeRO segment — and is mutually exclusive with
    ``grad_transform``."""
    if grad_transform is not None and grad_transform_wrapper is not None:
        raise ValueError(
            "grad_transform and grad_transform_wrapper are mutually "
            "exclusive (chain-in-front vs wrap-the-optimizer)"
        )
    try:
        base_ctor = OPTIMIZER_REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown optimizer {name!r}; available: {sorted(OPTIMIZER_REGISTRY)}"
        ) from None
    pre: list = []
    if clip_grad_norm is not None:
        if clip_grad_norm <= 0:
            raise ValueError(f"clip_grad_norm must be > 0, got {clip_grad_norm}")
        pre.append(optax.clip_by_global_norm(clip_grad_norm))
    if grad_transform is not None:
        pre.append(grad_transform)
    if pre or grad_transform_wrapper is not None:

        def ctor(*a, **kw):
            base = base_ctor(*a, **kw)
            if grad_transform_wrapper is not None:
                base = grad_transform_wrapper(base)
            return optax.chain(*pre, base) if pre else base

        # inject_hyperparams introspects the ctor signature:
        ctor.__signature__ = inspect.signature(base_ctor)
    else:
        ctor = base_ctor
    # Materialize numeric values for HP keys the ctor accepts with a
    # non-numeric default (e.g. sgd's momentum=None): inject_hyperparams
    # only exposes numeric args, and a regime must be able to retune any
    # param-group key in place (adjust_optimizer, utils.py:116-139).
    # momentum=0.0 is mathematically identical to momentum=None.
    sig = inspect.signature(ctor)
    for k in _HP_KEYS:
        if k == "learning_rate":  # passed explicitly below (adadelta's
            continue              # default is None — don't duplicate it)
        p = sig.parameters.get(k)
        if p is not None and p.default is None and k not in kwargs:
            kwargs[k] = 0.0
    return optax.inject_hyperparams(ctor)(learning_rate=learning_rate, **kwargs)


def regime_hp_kwargs(name: str, cfg: Dict[str, Any]) -> Dict[str, Any]:
    """The HP entries of a regime config that optimizer ``name``'s ctor
    accepts (others are ignored — the same tolerance torch shows for
    unknown param-group keys)."""
    ctor = OPTIMIZER_REGISTRY[name.lower()]
    sig = inspect.signature(ctor)
    return {
        k: cfg[k]
        for k in _HP_KEYS
        if k != "learning_rate" and k in cfg and k in sig.parameters
    }


def _hp_like(old: Any, value: Any) -> jnp.ndarray:
    """A hyperparam write that PRESERVES the old leaf's placement: the
    new scalar lands on the same sharding (mesh-replicated stays
    mesh-replicated). A bare ``jnp.asarray`` would produce an
    uncommitted default-device array, and any dispatch whose jit
    derives in_shardings from its args (the compressed shard_map step
    family) would see a different input layout and silently recompile
    — one stray post-warmup compile per hyperparam flip, which the
    budget-0 recompile fence of the scan-composition tests forbids."""
    new = jnp.asarray(value, dtype=jnp.asarray(old).dtype)
    sharding = getattr(old, "sharding", None)
    # Only mesh placements are pinned: an uncommitted scalar (fresh
    # tx.init state after a regime optimizer switch) must STAY
    # uncommitted — device_put would commit it to one device and clash
    # with the mesh-resident rest of the state at the next dispatch.
    if isinstance(sharding, jax.sharding.NamedSharding):
        new = jax.device_put(new, sharding)
    return new


class RegimeSchedule:
    """Epoch-indexed optimizer regime with sticky replay (utils.py:116-139).

    regime: {epoch: {"optimizer": name, "learning_rate": f, ...}} or a
    callable epoch -> dict. ``config_at(epoch)`` merges entries 0..epoch.
    """

    def __init__(self, regime: Dict[int, Dict[str, Any]] | Callable[[int], Dict] | None):
        self.regime = regime

    def config_at(self, epoch: int) -> Dict[str, Any]:
        if self.regime is None:
            return {}
        if callable(self.regime):
            merged: Dict[str, Any] = {}
            for e in range(epoch + 1):
                merged.update(self.regime(e) or {})
            return merged
        merged = {}
        for e in sorted(self.regime):
            if e <= epoch:
                merged.update(self.regime[e])
        return merged

    def optimizer_changed(self, epoch: int) -> bool:
        """Did the optimizer *class* change exactly at this epoch?"""
        if epoch == 0:
            return False
        prev = self.config_at(epoch - 1).get("optimizer")
        now = self.config_at(epoch).get("optimizer")
        return now is not None and now != prev

    def apply_hyperparams(self, opt_state: Any, epoch: int) -> Any:
        """Write the regime's numeric HPs for this epoch into an
        inject_hyperparams state (no moment reset)."""
        cfg = self.config_at(epoch)
        hp = getattr(opt_state, "hyperparams", None)
        if hp is None:
            return opt_state
        for k in _HP_KEYS:
            if k in cfg and k in hp:
                hp[k] = _hp_like(hp[k], cfg[k])
        return opt_state
