"""Optimizer registry + epoch-indexed "regime" scheduling.

Parity with the reference's ``__optimizers`` name->class dict (8 torch
optimizers, utils.py:104-113) and ``adjust_optimizer`` (utils.py:116-139):
a regime maps epoch -> settings dict; settings are *sticky* — the effective
config at epoch E is the merge of every entry with key <= E, replayed from
epoch 0 (exactly the reference's replay loop, utils.py:128-135).

Functional-JAX adaptation: hyperparameters (lr, momentum, ...) are updated
in place via optax.inject_hyperparams without resetting optimizer state;
changing the optimizer *class* mid-run rebuilds the transform with fresh
state (the reference's adjust_optimizer also reconstructs the torch
optimizer class, losing its state, utils.py:120-126 — same semantics).

``asgd`` (torch ASGD) is provided as SGD + Polyak tail averaging: the
transform keeps a running parameter average in its state (the torch
optimizer's ``ax`` buffer) while stepping as plain SGD.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, NamedTuple, Optional

import jax
import jax.flatten_util  # noqa: F401  (jax.flatten_util.ravel_pytree)
import jax.numpy as jnp
import optax

from ..ops.comm_compress import (
    CommPlan,
    exchange,
    make_plan,
    pad_flat,
    tree_size,
)


class _AsgdAvgState(NamedTuple):
    inner: Any
    avg: Any
    count: jnp.ndarray


def _asgd(learning_rate: float = 0.01) -> optax.GradientTransformation:
    """SGD with Polyak parameter averaging kept in state (torch ASGD's ax)."""
    inner = optax.sgd(learning_rate)

    def init(params):
        return _AsgdAvgState(
            inner=inner.init(params),
            avg=jax.tree.map(jnp.asarray, params),
            count=jnp.zeros((), jnp.int32),
        )

    def update(updates, state, params=None):
        new_updates, new_inner = inner.update(updates, state.inner, params)
        if params is not None:
            new_params = optax.apply_updates(params, new_updates)
            c = state.count + 1
            avg = jax.tree.map(
                lambda a, p: a + (p - a) / c.astype(p.dtype), state.avg, new_params
            )
        else:  # pragma: no cover - params always passed in this framework
            avg, c = state.avg, state.count
        return new_updates, _AsgdAvgState(new_inner, avg, c)

    return optax.GradientTransformation(init, update)


class SignCompressState(NamedTuple):
    """Error-feedback buffers for the 1-bit gradient exchange
    (ops/comm_compress, PERF.md "Gradient comms").

    Both carry a leading ``world`` axis — row *i* is worker *i*'s
    residual — so the buffers are ordinary global arrays in the
    checkpointed optimizer state (bitwise save/restore, the resilience
    invariant) while the compressed shard_map step shards that axis
    over 'data' (parallel/fsdp.compressed_state_specs): per-device cost
    is one fp32 residual, the same budget as a momentum buffer.

    ef_residual:  (world, padded) worker compression error — what the
                  worker's corrected gradient lost to sign quantization
                  (EF-SignSGD, Karimireddy et al., 2019).
    ef_residual2: (world, padded/world) segment-owner requantization
                  error from the exchange's second compressed phase
                  (the "server error" of 1-bit Adam).
    """

    ef_residual: jnp.ndarray
    ef_residual2: jnp.ndarray


def sign_compress(
    *,
    mode: str,
    world: int = 1,
    axis_name: Optional[str] = None,
    bucket_size: int = 1024,
    chunks: int = 4,
) -> optax.GradientTransformation:
    """1-bit gradient exchange as an optax transformation.

    Chain it in FRONT of the base optimizer: ``update`` flattens the
    incoming (local, per-worker) gradients, sign-compresses them per
    bucket, runs the two-phase compressed exchange over ``axis_name``
    (ops/comm_compress.exchange — this IS the DP all-reduce, so the
    step that hosts it must not pmean gradients again), and hands the
    decoded global update downstream. ``mode="sign_ef"`` additionally
    feeds both compression residuals back into the next step's input
    (held in the state, see SignCompressState); ``mode="sign"`` is the
    stateless Bernstein majority vote.

    With ``axis_name`` set, ``update`` must run inside the shard_map
    that owns that axis (the local view of the state buffers then has
    the leading axis sliced to 1); ``init`` always runs outside, on the
    global params. ``world=1`` needs no mesh and is the NumPy-oracle
    test configuration.
    """
    if mode not in ("sign", "sign_ef"):
        raise ValueError(
            f"unknown compression mode {mode!r} (have: sign, sign_ef)"
        )
    if axis_name is None and world != 1:
        raise ValueError("world > 1 requires an axis_name to exchange over")

    def _plan(n: int) -> CommPlan:
        return make_plan(
            n, world=world, mode=mode, bucket_size=bucket_size,
            chunks=chunks,
        )

    def init(params):
        if mode != "sign_ef":
            return optax.EmptyState()
        plan = _plan(tree_size(params))
        return SignCompressState(
            ef_residual=jnp.zeros((world, plan.padded), jnp.float32),
            ef_residual2=jnp.zeros((world, plan.seg), jnp.float32),
        )

    def update(updates, state, params=None):
        del params
        flat, unravel = jax.flatten_util.ravel_pytree(updates)
        plan = _plan(flat.size)
        flat = pad_flat(flat.astype(jnp.float32), plan)
        if mode == "sign_ef":
            corrected = flat + state.ef_residual[0]
            e2 = state.ef_residual2[0]
        else:
            corrected, e2 = flat, None
        combined, sent, e2_new = exchange(
            corrected, plan, axis_name=axis_name, e2=e2
        )
        new_updates = unravel(combined[: plan.n_params])
        if mode != "sign_ef":
            return new_updates, state
        # The pad tail never reaches the model (combined is sliced
        # before unraveling); zero its residual so phantom error can't
        # pollute the partial bucket's scale on later steps. e2 covers
        # one segment; only the last worker's segment holds pad.
        e1_new = (corrected - sent).at[plan.n_params:].set(0.0)
        if axis_name is not None:
            seg0 = jax.lax.axis_index(axis_name) * plan.seg
        else:
            seg0 = 0
        valid2 = seg0 + jnp.arange(plan.seg) < plan.n_params
        e2_new = jnp.where(valid2, e2_new, 0.0)
        return new_updates, SignCompressState(
            ef_residual=e1_new[None], ef_residual2=e2_new[None]
        )

    return optax.GradientTransformation(init, update)


OPTIMIZER_REGISTRY: Dict[str, Callable[..., optax.GradientTransformation]] = {
    "sgd": optax.sgd,
    "asgd": _asgd,
    "adam": optax.adam,
    "adamax": optax.adamax,
    "adagrad": optax.adagrad,
    "adadelta": optax.adadelta,
    "rprop": optax.rprop,
    "rmsprop": optax.rmsprop,
    # Large-batch optimizers (beyond reference parity): layerwise trust
    # ratios keep the bench's batch-4096 regime trainable at reference
    # accuracy recipes scaled up — the standard TPU large-batch choices.
    "lars": optax.lars,
    "lamb": optax.lamb,
}

# Hyperparameter keys accepted per optimizer (anything else in a regime
# entry is ignored with the same tolerance as torch param_group updates).
_HP_KEYS = ("learning_rate", "momentum", "b1", "b2", "eps", "weight_decay")


def make_optimizer(
    name: str, learning_rate: float, *, clip_grad_norm: float | None = None,
    grad_transform: optax.GradientTransformation | None = None,
    **kwargs: Any,
) -> optax.GradientTransformation:
    """Build a registry optimizer wrapped in inject_hyperparams so the
    learning rate (and other numeric HPs) can be retuned per epoch without
    resetting moment state.

    ``clip_grad_norm`` prepends global-norm gradient clipping INSIDE the
    inject_hyperparams wrapper — the hyperparams dict stays the outermost
    state attribute, so the Trainer's per-epoch lr/regime writes keep
    working (chaining outside would bury it and silently disable the lr
    schedule). ``grad_transform`` (e.g. ``sign_compress``) chains after
    the clip and before the optimizer, inside the same wrapper for the
    same reason — its state (the EF residuals) rides in ``opt_state``
    and therefore checkpoints with it."""
    try:
        base_ctor = OPTIMIZER_REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown optimizer {name!r}; available: {sorted(OPTIMIZER_REGISTRY)}"
        ) from None
    pre: list = []
    if clip_grad_norm is not None:
        if clip_grad_norm <= 0:
            raise ValueError(f"clip_grad_norm must be > 0, got {clip_grad_norm}")
        pre.append(optax.clip_by_global_norm(clip_grad_norm))
    if grad_transform is not None:
        pre.append(grad_transform)
    if pre:

        def ctor(*a, **kw):
            return optax.chain(*pre, base_ctor(*a, **kw))

        # inject_hyperparams introspects the ctor signature:
        ctor.__signature__ = inspect.signature(base_ctor)
    else:
        ctor = base_ctor
    # Materialize numeric values for HP keys the ctor accepts with a
    # non-numeric default (e.g. sgd's momentum=None): inject_hyperparams
    # only exposes numeric args, and a regime must be able to retune any
    # param-group key in place (adjust_optimizer, utils.py:116-139).
    # momentum=0.0 is mathematically identical to momentum=None.
    sig = inspect.signature(ctor)
    for k in _HP_KEYS:
        if k == "learning_rate":  # passed explicitly below (adadelta's
            continue              # default is None — don't duplicate it)
        p = sig.parameters.get(k)
        if p is not None and p.default is None and k not in kwargs:
            kwargs[k] = 0.0
    return optax.inject_hyperparams(ctor)(learning_rate=learning_rate, **kwargs)


def regime_hp_kwargs(name: str, cfg: Dict[str, Any]) -> Dict[str, Any]:
    """The HP entries of a regime config that optimizer ``name``'s ctor
    accepts (others are ignored — the same tolerance torch shows for
    unknown param-group keys)."""
    ctor = OPTIMIZER_REGISTRY[name.lower()]
    sig = inspect.signature(ctor)
    return {
        k: cfg[k]
        for k in _HP_KEYS
        if k != "learning_rate" and k in cfg and k in sig.parameters
    }


class RegimeSchedule:
    """Epoch-indexed optimizer regime with sticky replay (utils.py:116-139).

    regime: {epoch: {"optimizer": name, "learning_rate": f, ...}} or a
    callable epoch -> dict. ``config_at(epoch)`` merges entries 0..epoch.
    """

    def __init__(self, regime: Dict[int, Dict[str, Any]] | Callable[[int], Dict] | None):
        self.regime = regime

    def config_at(self, epoch: int) -> Dict[str, Any]:
        if self.regime is None:
            return {}
        if callable(self.regime):
            merged: Dict[str, Any] = {}
            for e in range(epoch + 1):
                merged.update(self.regime(e) or {})
            return merged
        merged = {}
        for e in sorted(self.regime):
            if e <= epoch:
                merged.update(self.regime[e])
        return merged

    def optimizer_changed(self, epoch: int) -> bool:
        """Did the optimizer *class* change exactly at this epoch?"""
        if epoch == 0:
            return False
        prev = self.config_at(epoch - 1).get("optimizer")
        now = self.config_at(epoch).get("optimizer")
        return now is not None and now != prev

    def apply_hyperparams(self, opt_state: Any, epoch: int) -> Any:
        """Write the regime's numeric HPs for this epoch into an
        inject_hyperparams state (no moment reset)."""
        cfg = self.config_at(epoch)
        hp = getattr(opt_state, "hyperparams", None)
        if hp is None:
            return opt_state
        for k in _HP_KEYS:
            if k in cfg and k in hp:
                hp[k] = jnp.asarray(cfg[k], dtype=jnp.asarray(hp[k]).dtype)
        return opt_state
