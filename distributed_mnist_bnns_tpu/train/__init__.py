from .optim import OPTIMIZER_REGISTRY, make_optimizer, RegimeSchedule
from .trainer import TrainConfig, Trainer, TrainState, make_train_step, make_eval_step

__all__ = [
    "OPTIMIZER_REGISTRY",
    "make_optimizer",
    "RegimeSchedule",
    "TrainConfig",
    "Trainer",
    "TrainState",
    "make_train_step",
    "make_eval_step",
]
