from .optim import (
    OPTIMIZER_REGISTRY,
    RegimeSchedule,
    make_optimizer,
    regime_hp_kwargs,
)
from .trainer import (
    TrainConfig,
    Trainer,
    TrainState,
    clamp_latent,
    make_eval_step,
    make_eval_epoch_fn,
    make_masked_eval_step,
    make_step_body,
    make_train_epoch_fn,
    make_train_scan,
    make_train_step,
)

__all__ = [
    "OPTIMIZER_REGISTRY",
    "make_optimizer",
    "regime_hp_kwargs",
    "RegimeSchedule",
    "TrainConfig",
    "Trainer",
    "TrainState",
    "clamp_latent",
    "make_train_step",
    "make_train_scan",
    "make_train_epoch_fn",
    "make_step_body",
    "make_eval_step",
    "make_masked_eval_step",
    "make_eval_epoch_fn",
]
